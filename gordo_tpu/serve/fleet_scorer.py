"""Stacked multi-machine serving: many models resident per chip, scored in
one dispatch.

Reference equivalent: none — the reference serves one model per pod, so
aggregate project throughput is bounded by per-request Python/Flask
overhead times N pods.  SURVEY.md §8 step 6 calls for the TPU-native
answer: stack every (structurally identical) machine's params on device
and score a whole project's stream as ONE vmapped fused program — a
bucket of tiny per-tag scoring programs becomes MXU-filling batched GEMMs,
exactly like the fleet trainer.

Used by the bulk serving route (``POST .../_bulk/anomaly/prediction``) and
the replayed-stream benchmark (BASELINE config 5).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gordo_tpu import artifacts
from gordo_tpu import compile as compile_plane
from gordo_tpu.anomaly.diff import scores_fn
from gordo_tpu.ops.windows import make_windows
from gordo_tpu.serve import precision
from gordo_tpu.serve.scorer import (
    SMOOTH_ONE_SHOT_BOUND,
    CompiledScorer,
    _DISPATCHES,
    _H2D,
    _bucket_rows,
    _extract_chain,
    _rolling_median,
    short_rows_message,
)

#: the ONE measured windows-tensor ceiling (scorer.SMOOTH_ONE_SHOT_BOUND:
#: 2^27.5 compiles, 2^28.5 kills XLA — v5e probe, r4), applied here across
#: the stacked machine axis.  NOTE: a source-level alias — editing the
#: scorer constant updates both, but a *runtime* rebind of
#: scorer.SMOOTH_ONE_SHOT_BOUND (monkeypatch, dynamic re-probe) does not
#: propagate here; rebind both names in that case.
SMOOTH_ELEMENT_BOUND = SMOOTH_ONE_SHOT_BOUND


def _fleet_score_core(
    module,
    scaler_classes,
    mode,
    lookback,
    det_cls,
    with_thresholds,
    smooth_window,
    dtype,           # serving precision (static: keys the executable)
    scaler_stats,    # tuple of stacked stats pytrees, leaves (M, ...)
    params,          # stacked params pytree, leaves (M, ...)
    det_stats,       # stacked detector-scaler stats
    agg_thresholds,  # (M,) stacked aggregate thresholds (or None)
    X,               # (M, N, F)
):
    """The fused anomaly program of ``serve.scorer``, vmapped over the
    machine axis, at serving precision ``dtype`` (casts are identity for
    float32 and for leaves already stored reduced).  Outputs leave the
    program as float32 — the response schema is dtype-invariant; the
    confidence divide runs f32 against never-quantized thresholds."""
    scaler_stats = precision.cast_params(scaler_stats, dtype)
    params = precision.cast_params(params, dtype)
    det_stats = precision.cast_params(det_stats, dtype)
    Xc = precision.cast_input(X, dtype)

    def one(stats_i, params_i, det_i, x):
        xs = x
        for cls, st in zip(scaler_classes, stats_i):
            xs = cls.apply(st, xs)
        if mode == "none":
            inputs = xs
        elif mode == "ae":
            inputs = make_windows(xs, lookback)
        else:  # forecast
            inputs = make_windows(xs[:-1], lookback)
        pred = module.apply({"params": params_i}, inputs)
        offset = x.shape[0] - pred.shape[0]
        tag, total = scores_fn(det_cls, det_i, x[offset:], pred)
        if smooth_window:
            tag = _rolling_median(tag, smooth_window)
            total = _rolling_median(total, smooth_window)
        return pred, tag, total

    pred, tag, total = jax.vmap(one)(scaler_stats, params, det_stats, Xc)
    total = total.astype(jnp.float32)
    out = {
        "model-output": pred.astype(jnp.float32),
        "tag-anomaly-scores": tag.astype(jnp.float32),
        "total-anomaly-score": total,
    }
    if with_thresholds:
        out["anomaly-confidence"] = total / jnp.maximum(
            agg_thresholds[:, None].astype(jnp.float32), 1e-12
        )
    return out


_STATIC_ARGS = (
    "module", "scaler_classes", "mode", "lookback", "det_cls",
    "with_thresholds", "smooth_window", "dtype",
)

#: the full-bucket stacked program, compile-plane-owned: warmup
#: AOT-compiles it per (bucket signature, row bucket) before readiness
_fleet_score_program = compile_plane.program(
    "serve.fleet", _fleet_score_core, static_argnames=_STATIC_ARGS
)


def _fleet_score_subset_core(
    module,
    scaler_classes,
    mode,
    lookback,
    det_cls,
    with_thresholds,
    smooth_window,
    dtype,
    scaler_stats,
    params,
    det_stats,
    agg_thresholds,
    idx,             # (m_sub,) int32 positions into the stacked machine axis
    X,               # (m_sub, N, F)
):
    """Score a SUBSET of a bucket's machines: gather their stacked slots on
    device, then run the same fused program at the subset size.

    ``idx`` is a traced array, so which machines are requested never
    recompiles — only the subset SIZE does, and callers pad that to a power
    of two.  This is what makes small coalesced rounds cheap: an 8-machine
    dispatch against a 512-machine bucket computes (and transfers back)
    8 slots, not 512.
    """
    take = lambda t: jax.tree.map(lambda a: a[idx], t)  # noqa: E731
    return _fleet_score_core(
        module, scaler_classes, mode, lookback, det_cls, with_thresholds,
        smooth_window, dtype,
        take(scaler_stats),
        take(params),
        take(det_stats),
        None if agg_thresholds is None else agg_thresholds[idx],
        X,
    )


_fleet_score_subset_program = compile_plane.program(
    "serve.fleet_subset", _fleet_score_subset_core,
    static_argnames=_STATIC_ARGS,
)


class _Bucket:
    """One structurally identical group of machines, params stacked.

    With ``mesh`` (a ``("models", "data")`` fleet mesh spanning >1 device),
    the stacked machine axis is padded to a multiple of the model-shard
    count and placed with a ``models``-axis ``NamedSharding`` — the fused
    program is a pure map over machines, so XLA partitions one serving
    dispatch across every chip with zero collectives.  This is the serving
    twin of the fleet trainer's sharding (``parallel/fleet.py``).
    """

    def __init__(
        self,
        names: List[str],
        chains: List[Dict[str, Any]],
        mesh: Optional[Any] = None,
        prestacked: Optional[Dict[str, Any]] = None,
        dtype: Optional[str] = None,
    ):
        self.names = names
        c0 = chains[0]
        self.module = c0["module"]
        self.scaler_classes = tuple(cls for cls, _ in c0["scalers"])
        self.mode = c0["mode"]
        self.lookback = c0["lookback"]
        det0 = c0["detector"]
        self.det_cls = det0["scaler_cls"]
        self.smooth_window = det0["window"]
        #: the serving precision this bucket's stacked programs dispatch
        #: at; its stacked float tensors are STORED at the matching
        #: storage dtype (bf16 halves residency and the pack transfer)
        self.dtype = (
            precision.canonical(dtype) if dtype else precision.serve_dtype()
        )
        self.with_thresholds = all(
            c["detector"]["feature_thresholds"] is not None for c in chains
        )

        from gordo_tpu.mesh import MODEL_AXIS

        self.mesh = (
            mesh
            if mesh is not None and mesh.shape.get(MODEL_AXIS, 1) > 1
            else None
        )
        #: stacked machine-axis length on device (== len(names) without a
        #: mesh; padded to a shard multiple with one)
        self.m_pad = len(names)

        if prestacked is not None:
            self._init_prestacked(prestacked)
        else:
            self._init_stacking(chains)
        #: authoritative input width (detector scaler stats are per-feature
        #: arrays), used to reject malformed requests per machine instead
        #: of letting one bad array sink a whole stacked dispatch
        det_leaves = jax.tree.leaves(self.det_stats)
        self.n_features = (
            int(det_leaves[0].shape[-1]) if det_leaves else None
        )
        #: pinned host stacking buffers keyed by (machines, rows, features),
        #: reused across score_all calls while request shapes repeat;
        #: LRU-bounded so a long-lived server with varied request shapes
        #: can't accumulate unbounded host memory; guarded by _lock —
        #: concurrent bulk requests run score_all from executor threads
        self._stack_bufs: "OrderedDict[Tuple[int, int, int], np.ndarray]" = (
            OrderedDict()
        )
        self._lock = threading.Lock()

    def _init_stacking(self, chains: List[Dict[str, Any]]) -> None:
        """The v1 path: per-machine chain arrays stack leaf by leaf (one
        host gather + implicit transfer per leaf).

        With a mesh the stack/cast/pad all stay host-side (numpy) so the
        sharded ``jax.device_put`` at the end is the ONLY host->device
        copy per leaf; stacking through jnp would first place every leaf
        on the default device, then copy it again for the sharded layout.
        """
        mesh = self.mesh
        if mesh is None:
            stack = lambda trees: jax.tree.map(  # noqa: E731
                lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *trees
            )
        else:
            stack = lambda trees: jax.tree.map(  # noqa: E731
                lambda *xs: np.stack([np.asarray(x) for x in xs]), *trees
            )
        self.params = stack([c["params"] for c in chains])
        self.scaler_stats = tuple(
            stack([c["scalers"][i][1] for c in chains])
            for i in range(len(self.scaler_classes))
        )
        self.det_stats = stack([c["detector"]["scaler_stats"] for c in chains])
        if self.dtype != "float32":
            # reduced-precision serving stores the stacked float tensors
            # at the storage dtype (bf16): half the device residency, and
            # the in-program compute cast becomes an identity
            if mesh is None:
                self.params = precision.cast_storage(self.params, self.dtype)
                self.scaler_stats = precision.cast_storage(
                    self.scaler_stats, self.dtype
                )
                self.det_stats = precision.cast_storage(
                    self.det_stats, self.dtype
                )
            else:
                # host-side equivalent of cast_storage — casting through
                # jnp here would defeat the single-transfer property
                store = precision.storage_np_dtype(self.dtype)
                cast = lambda tree: jax.tree.map(  # noqa: E731
                    lambda a: (
                        a.astype(store)
                        if np.issubdtype(a.dtype, np.floating) else a
                    ),
                    tree,
                )
                self.params = cast(self.params)
                self.scaler_stats = cast(self.scaler_stats)
                self.det_stats = cast(self.det_stats)
        if self.with_thresholds:
            # host copies kept alongside the device arrays: per-machine
            # response assembly reads thresholds once per call per machine,
            # and a device-array index there would issue hundreds of tiny
            # device->host transfers per bulk request (measured r4: 9.2s of
            # a 10s call over the TPU tunnel)
            self.thresholds_np = np.stack(
                [
                    np.asarray(c["detector"]["feature_thresholds"])
                    for c in chains
                ]
            )
            self.agg_thresholds_np = np.asarray(
                [
                    float(c["detector"]["aggregate_threshold"])
                    for c in chains
                ],
                np.float32,
            )
            # only the aggregate goes to device (the program's confidence
            # divide); per-feature thresholds are response-assembly-only and
            # a device copy would just pin unused memory.  With a mesh the
            # device copy happens sharded in the block below instead.
            self.agg_thresholds = (
                jnp.asarray(self.agg_thresholds_np) if mesh is None else None
            )
        else:
            self.thresholds_np = None
            self.agg_thresholds_np = None
            self.agg_thresholds = None
        if mesh is not None:
            from gordo_tpu.mesh import (
                MODEL_AXIS,
                model_sharding,
                pad_to_multiple,
                place,
            )

            shards = mesh.shape[MODEL_AXIS]
            self.m_pad = pad_to_multiple(len(self.names), shards)
            pad = self.m_pad - len(self.names)

            def shard(tree):
                def one(a):
                    if pad:
                        a = np.concatenate(
                            [a, np.repeat(a[:1], pad, axis=0)]
                        )
                    return place(a, model_sharding(mesh, a.ndim - 1))

                return jax.tree.map(one, tree)

            self.params = shard(self.params)
            self.scaler_stats = shard(self.scaler_stats)
            self.det_stats = shard(self.det_stats)
            if self.agg_thresholds_np is not None:
                agg = np.asarray(self.agg_thresholds_np)
                if pad:
                    agg = np.concatenate([agg, np.repeat(agg[:1], pad)])
                self.agg_thresholds = place(agg, model_sharding(mesh, 0))
            self._x_sharding = model_sharding(self.mesh, 2)

    def _init_prestacked(self, prestacked: Dict[str, Any]) -> None:
        """The v2 pack path: the artifact store already holds this
        bucket's arrays stacked (M_pack, ...) and memory-mapped per
        (signature, bucket) pack, so each pack ships to the device as
        ONE ``artifacts.to_device`` call — zero host copies — and a
        multi-pack bucket concatenates the transferred trees on device.
        Dispatch geometry (the stacked machine-axis length) is identical
        to the v1 stacking path's, so scoring stays bitwise-equal to a
        v1 load of the same models.
        """
        self.thresholds_np = (
            prestacked["feature_thresholds"] if self.with_thresholds else None
        )
        self.agg_thresholds_np = (
            prestacked["agg"] if self.with_thresholds else None
        )
        pack_hosts = prestacked["packs"]
        if self.mesh is not None:
            from gordo_tpu.mesh import (
                MODEL_AXIS,
                model_sharding,
                pad_to_multiple,
                place,
            )

            shards = self.mesh.shape[MODEL_AXIS]
            self.m_pad = pad_to_multiple(len(self.names), shards)
            pad = self.m_pad - len(self.names)

            def stitch(*parts):
                # load-time pack stitching (NOT the request path — the
                # host-math lint gate scopes a request-path "assemble")
                a = (
                    parts[0] if len(parts) == 1
                    else np.concatenate(parts, axis=0)
                )
                if pad:
                    a = np.concatenate([a, np.repeat(a[:1], pad, axis=0)])
                return a

            # sharded placement needs host-side pad/concat copies anyway;
            # still ONE counted transfer for the whole bucket
            host = jax.tree.map(stitch, *pack_hosts)
            shardings = jax.tree.map(
                lambda a: model_sharding(self.mesh, a.ndim - 1), host
            )
            dev = artifacts.to_device(
                host, shardings,
                dtype=precision.storage_np_dtype(self.dtype),
            )
            self._x_sharding = model_sharding(self.mesh, 2)
            self.params, self.scaler_stats, self.det_stats = dev
            self.agg_thresholds = None
            if self.with_thresholds:
                agg = self.agg_thresholds_np
                if pad:
                    agg = np.concatenate([agg, np.repeat(agg[:1], pad)])
                self.agg_thresholds = place(
                    jnp.asarray(agg), model_sharding(self.mesh, 0)
                )
            return
        devs = [
            artifacts.to_device(
                h, dtype=precision.storage_np_dtype(self.dtype)
            )
            for h in pack_hosts
        ]
        dev = devs[0] if len(devs) == 1 else jax.tree.map(
            lambda *xs: jnp.concatenate(xs, axis=0), *devs
        )
        self.params, self.scaler_stats, self.det_stats = dev
        self.agg_thresholds = (
            jnp.asarray(self.agg_thresholds_np)
            if self.with_thresholds else None
        )

    #: max retained stacking buffers per bucket (power-of-two shape
    #: bucketing keeps distinct shapes few; 4 covers a steady mix of bulk +
    #: coalesced sizes while bounding worst-case host residency)
    MAX_STACK_BUFS = 4

    @staticmethod
    def fill_slot(stacked: np.ndarray, i: int, a: np.ndarray) -> None:
        """Write machine rows into dispatch slot ``i`` with repeat-last row
        padding — the ONE padding scheme both subset and full-bucket
        dispatches must share (divergence would make partial- and
        full-bucket results differ for the same machine)."""
        stacked[i, : a.shape[0]] = a
        stacked[i, a.shape[0]:] = a[-1:]

    def stack_buffer(self, shape: Tuple[int, int, int]) -> np.ndarray:
        """Pinned stacking buffer for ``shape`` (call with ``_lock`` held)."""
        buf = self._stack_bufs.get(shape)
        if buf is None:
            buf = self._stack_bufs[shape] = np.empty(shape, np.float32)
            while len(self._stack_bufs) > self.MAX_STACK_BUFS:
                self._stack_bufs.popitem(last=False)
        else:
            self._stack_bufs.move_to_end(shape)
        return buf

    def _program_prefix(self) -> Tuple:
        """The stacked programs' leading arguments — dispatch and AOT
        warmup must assemble them identically (same objects, same static
        values) or warmed executables would never be looked up."""
        return (
            self.module,
            self.scaler_classes,
            self.mode,
            self.lookback,
            self.det_cls,
            self.with_thresholds,
            self.smooth_window,
            self.dtype,
            self.scaler_stats,
            self.params,
            self.det_stats,
            self.agg_thresholds,
        )

    def score(self, X_stack: np.ndarray) -> Dict[str, np.ndarray]:
        if self.mesh is not None:
            # host array straight to its shards (committed sharding -> XLA
            # partitions the whole fused program over the fleet axis, a
            # pure map with no collectives); going via jnp.asarray first
            # would stage the full array on device 0 and pay a second
            # device-to-device scatter
            from gordo_tpu.mesh import place

            _H2D.inc(1.0, "serve.fleet")
            X = place(np.asarray(X_stack, np.float32), self._x_sharding)
        else:
            _H2D.inc(1.0, "serve.fleet")
            X = jnp.asarray(X_stack, jnp.float32)
        _DISPATCHES.inc(1.0, "serve.fleet")
        return _fleet_score_program(*self._program_prefix(), X)

    def score_subset(
        self, X_stack: np.ndarray, idx: np.ndarray
    ) -> Dict[str, np.ndarray]:
        _H2D.inc(1.0, "serve.fleet_subset")
        _DISPATCHES.inc(1.0, "serve.fleet_subset")
        return _fleet_score_subset_program(
            *self._program_prefix(),
            jnp.asarray(idx, jnp.int32),
            jnp.asarray(X_stack, jnp.float32),
        )

    def warm_programs(
        self, row_sizes: "List[int]"
    ) -> "List[Tuple[str, int, float]]":
        """AOT-compile this bucket's stacked dispatch family for each row
        bucket: the full-bucket program (the ``_bulk`` route) and — for
        multi-machine buckets — the 1-machine subset gather (the
        coalescer's common case).  Shape structs only; nothing executes.
        Returns ``[(label, rows, compile_seconds), ...]``."""
        n_feat = self.n_features or 1
        out: "List[Tuple[str, int, float]]" = []
        for rows in row_sizes:
            x_kw = {}
            if self.mesh is not None:
                x_kw["sharding"] = self._x_sharding
            X_full = jax.ShapeDtypeStruct(
                (self.m_pad, int(rows), n_feat), jnp.float32, **x_kw
            )
            out.append((
                "serve.fleet/full", int(rows),
                _fleet_score_program.warm(*self._program_prefix(), X_full),
            ))
            if len(self.names) > 1:
                idx = jax.ShapeDtypeStruct((1,), jnp.int32)
                X_sub = jax.ShapeDtypeStruct(
                    (1, int(rows), n_feat), jnp.float32
                )
                out.append((
                    "serve.fleet/subset", int(rows),
                    _fleet_score_subset_program.warm(
                        *self._program_prefix(), idx, X_sub
                    ),
                ))
        return out


class _PrestackMiss(Exception):
    """A chain leaf did not map back to its pack's stacked tensors —
    fall back to the generic per-leaf stacking path."""


def _delta_bucket(
    bucket: _Bucket, store, touched: List[str], dtype: str
) -> _Bucket:
    """One bucket's successor after a delta generation flip: re-stack
    ONLY the touched pack runs into the prestacked device buffers.

    The delta contract (``artifacts.delta_write``) guarantees stable
    membership, slots, and leaf shapes, so the new bucket's device
    tensors are assembled from the OLD bucket's device arrays (zero-copy
    device slices for every untouched pack run) plus one
    ``artifacts.to_device`` per TOUCHED pack — host→device traffic is
    O(changed packs), and identical shapes mean the compile plane
    resolves every program of the new bucket from cache (zero compiles).
    Raises :class:`_PrestackMiss` whenever the geometry drifted (members
    moved packs, slots went non-contiguous, sharded placement) — the
    caller falls back to a full restack, never serves a misaligned view.
    """
    if bucket.mesh is not None:
        # sharded buckets interleave pad slots — rebuild wholesale
        raise _PrestackMiss()
    member_set = set(bucket.names)
    pack_ids: List[str] = []
    for n in bucket.names:
        if n not in store:
            raise _PrestackMiss()
        pid = store.location(n)[0]
        if pid not in pack_ids:
            pack_ids.append(pid)
    runs: Dict[str, Tuple[int, int, int]] = {}
    expect: List[str] = []
    for pid in pack_ids:
        live = store.machines_of(pid)
        owned = [i for i, m in enumerate(live) if m in member_set]
        lo, hi = owned[0], owned[-1] + 1
        if owned != list(range(lo, hi)):
            raise _PrestackMiss()
        runs[pid] = (lo, hi, len(live))
        expect.extend(live[lo:hi])
    if expect != list(bucket.names):
        raise _PrestackMiss()
    touched_packs: List[str] = []
    for n in touched:
        pid = store.location(n)[0]
        if pid not in touched_packs:
            touched_packs.append(pid)

    def lift(pid, live_count, a):
        loc = store.leaf_of(a)
        if loc is None or loc[0] != pid:
            raise _PrestackMiss()
        stacked = store.stacked(pid)[loc[1]]
        if stacked.shape[0] != live_count:
            raise _PrestackMiss()
        lo, hi, _ = runs[pid]
        return stacked[lo:hi]

    new_parts: Dict[str, Any] = {}
    thr_rows: Dict[str, np.ndarray] = {}
    for pid in touched_packs:
        lo, hi, n_live = runs[pid]
        rep = _extract_chain(store.load_model(store.machines_of(pid)[lo]))
        if rep is None:
            raise _PrestackMiss()
        take = lambda a, p=pid, m=n_live: lift(p, m, a)  # noqa: E731
        host = (
            jax.tree.map(take, rep["params"]),
            tuple(jax.tree.map(take, st) for _, st in rep["scalers"]),
            jax.tree.map(take, rep["detector"]["scaler_stats"]),
        )
        new_parts[pid] = artifacts.to_device(
            host, dtype=precision.storage_np_dtype(dtype)
        )
        if bucket.with_thresholds:
            ft = rep["detector"]["feature_thresholds"]
            if ft is None:
                raise _PrestackMiss()
            thr_rows[pid] = np.asarray(take(ft))

    old_leaves, treedef = jax.tree.flatten(
        (bucket.params, bucket.scaler_stats, bucket.det_stats)
    )
    parts_leaves = {
        pid: jax.tree.flatten(t)[0] for pid, t in new_parts.items()
    }
    offsets: Dict[str, Tuple[int, int]] = {}
    pos = 0
    for pid in pack_ids:
        lo, hi, _ = runs[pid]
        offsets[pid] = (pos, pos + (hi - lo))
        pos += hi - lo
    new_leaves = []
    for i, old_leaf in enumerate(old_leaves):
        pieces = []
        for pid in pack_ids:
            start, stop = offsets[pid]
            if pid in parts_leaves:
                pieces.append(parts_leaves[pid][i])
            else:
                # untouched run: a device slice of the resident stacked
                # tensor — no host copy, no transfer
                pieces.append(old_leaf[start:stop])
        new_leaves.append(
            pieces[0] if len(pieces) == 1
            else jnp.concatenate(pieces, axis=0)
        )
    params, scaler_stats, det_stats = jax.tree.unflatten(
        treedef, new_leaves
    )

    thresholds_np = bucket.thresholds_np
    agg_np = bucket.agg_thresholds_np
    if bucket.with_thresholds:
        # COPIES, never in-place: in-flight dispatches against the old
        # bucket assemble from its threshold arrays after their device
        # work completes — mutating them would mix generations within
        # one response
        thresholds_np = np.array(bucket.thresholds_np, copy=True)
        agg_np = np.array(bucket.agg_thresholds_np, copy=True)
        for pid in touched_packs:
            start, stop = offsets[pid]
            thresholds_np[start:stop] = thr_rows[pid]
        pos_of = {n: i for i, n in enumerate(bucket.names)}
        for n in touched:
            c = _extract_chain(store.load_model(n))
            if c is None:
                raise _PrestackMiss()
            agg_np[pos_of[n]] = float(
                c["detector"]["aggregate_threshold"] or 0.0
            )

    nb = _Bucket.__new__(_Bucket)
    for attr in (
        "names", "module", "scaler_classes", "mode", "lookback",
        "det_cls", "smooth_window", "dtype", "with_thresholds", "mesh",
        "m_pad", "n_features",
    ):
        setattr(nb, attr, getattr(bucket, attr))
    nb.params, nb.scaler_stats, nb.det_stats = (
        params, scaler_stats, det_stats
    )
    nb.thresholds_np = thresholds_np
    nb.agg_thresholds_np = agg_np
    nb.agg_thresholds = (
        jnp.asarray(agg_np) if bucket.with_thresholds else None
    )
    # share the dispatch lock + pinned stacking buffers with the
    # predecessor: old-scorer and new-scorer dispatches against "the
    # same" bucket must serialize on one lock or a shared buffer could
    # be overwritten mid-transfer during the handover window
    nb._lock = bucket._lock
    nb._stack_bufs = bucket._stack_bufs
    return nb


def _prestack_group(
    store, names: List[str], chains: List[Dict[str, Any]]
):
    """Zero-copy stacked arrays for a pack-backed signature group.

    Bucketing stays at the v1 granularity (one bucket per structural
    signature — dispatch geometry, and therefore XLA codegen and bitwise
    outputs, must not depend on how the build chunked its packs).  Each
    pack contributes its stacked ``(M_pack, ...)`` memmap tensors as ONE
    whole-pack device transfer; a multi-pack bucket concatenates the
    transferred trees on device.

    A pack may also contribute a CONTIGUOUS RUN of its slots — the
    fleet-sharded serving case: shard slices and pack chunks are both
    name-sorted, so a replica's boundary cuts a pack into a basic numpy
    slice of the stacked tensors (still a zero-copy view, still one
    ``to_device`` for that pack's contribution).  A pack whose in-group
    machines are NOT slot-contiguous (interleaved bucketing) falls back
    to the generic stacking path, as before.

    Succeeds only when every machine of the group is pack-backed and
    every chain array of each contributed run's first machine maps back
    to a stacked tensor.  Returns ``(prestacked, names, chains)``
    reordered to pack-slot order, or ``(None, names, chains)`` unchanged.
    """
    by_name = dict(zip(names, chains))
    group = set(names)
    pack_ids: List[str] = []
    for n in names:
        if n not in store:
            return None, names, chains
        pid = store.location(n)[0]
        if pid not in pack_ids:
            pack_ids.append(pid)
    slot_orders: Dict[str, List[str]] = {}
    slot_runs: Dict[str, Tuple[int, int]] = {}
    for pid in pack_ids:
        live = store.machines_of(pid)
        owned_pos = [i for i, m in enumerate(live) if m in group]
        lo, hi = owned_pos[0], owned_pos[-1] + 1
        if owned_pos != list(range(lo, hi)):
            # in-group slots are interleaved with foreign ones — a view
            # can't express that; stacked rows would not align
            return None, names, chains
        slot_orders[pid] = live[lo:hi]
        slot_runs[pid] = (lo, hi)
    pack_ids.sort(key=lambda p: slot_orders[p][0])

    def lift(pid, live_count, a):
        loc = store.leaf_of(a)
        if loc is None or loc[0] != pid:
            raise _PrestackMiss()
        stacked = store.stacked(pid)[loc[1]]
        if stacked.shape[0] != live_count:
            # superseded slots still occupy stacked rows — row i would
            # no longer be machine i of this bucket
            raise _PrestackMiss()
        lo, hi = slot_runs[pid]
        return stacked[lo:hi]  # basic slice: still a zero-copy view

    pack_hosts = []
    thr_parts: List[Any] = []
    want_thr = all(
        c["detector"]["feature_thresholds"] is not None for c in chains
    )
    try:
        for pid in pack_ids:
            live = slot_orders[pid]
            c0 = by_name[live[0]]
            n_live = len(store.machines_of(pid))
            take = lambda a, p=pid, m=n_live: lift(p, m, a)  # noqa: E731
            pack_hosts.append((
                jax.tree.map(take, c0["params"]),
                tuple(
                    jax.tree.map(take, stats) for _, stats in c0["scalers"]
                ),
                jax.tree.map(take, c0["detector"]["scaler_stats"]),
            ))
            if want_thr:
                thr_parts.append(take(c0["detector"]["feature_thresholds"]))
    except _PrestackMiss:
        return None, names, chains

    names = [n for pid in pack_ids for n in slot_orders[pid]]
    chains = [by_name[n] for n in names]
    thr = None
    if want_thr:
        # single pack: the memmap view itself (zero copy); multi-pack:
        # one bounded host concat of the (M, n_tags) threshold rows
        thr = thr_parts[0] if len(thr_parts) == 1 else np.concatenate(
            thr_parts
        )
    prestacked = {
        "packs": pack_hosts,
        "feature_thresholds": thr,
        "agg": np.asarray(
            [
                float(c["detector"]["aggregate_threshold"] or 0.0)
                for c in chains
            ],
            np.float32,
        ),
    }
    return prestacked, names, chains


def _hint_group(
    hint: Dict[str, Any], names: List[str], chains: List[Dict]
) -> Tuple[Optional[Dict[str, Any]], List[str], List[Dict]]:
    """Adopt a builder-supplied prestack for this signature group.

    The fleet builder's collect side fetches each chunk's results as
    stacked ``(M, ...)`` host arrays and hands the per-machine detectors
    zero-copy views; ``hint`` re-exposes those stacked arrays whole
    (``PendingFleetBuild.prestacked``).  When this group is exactly the
    hinted fleet, the bucket initializes through the prestacked path —
    one ``to_device`` per pack — instead of re-stacking the per-machine
    views leaf by leaf.  Row order follows the hint (group-dispatch
    order); bucket semantics don't depend on name order.  Any mismatch —
    a subset fleet, mixed signatures splitting the models across groups —
    falls back to the generic stacking path unchanged.
    """
    hinted = hint.get("names")
    if (
        hinted is None
        or len(hinted) != len(names)
        or set(hinted) != set(names)
    ):
        return None, names, chains
    by_name = dict(zip(names, chains))
    names = list(hinted)
    return (
        {k: hint[k] for k in ("packs", "feature_thresholds", "agg")},
        names,
        [by_name[n] for n in names],
    )


def _signature(chain: Dict[str, Any]) -> Optional[Tuple]:
    det = chain["detector"]
    if det is None:
        return None
    if det["feature_thresholds"] is None and det["require_thresholds"]:
        # the per-machine path refuses to serve this model; route it through
        # the fallback so the same per-machine error surfaces here
        return None
    return (
        chain["module"],                      # flax modules: frozen, hashable
        tuple(cls for cls, _ in chain["scalers"]),
        chain["mode"],
        chain["lookback"],
        det["scaler_cls"],
        det["window"],
        det["feature_thresholds"] is not None,
    )


class FleetDispatch:
    """Completed device dispatches whose per-machine result assembly is
    deferred.

    ``FleetScorer.dispatch_all`` returns one of these after the device
    work (stacking, dispatch, device→host transfer) is done;
    :meth:`assemble` performs the remaining host-side numpy slicing and
    dict building.  The split exists for the coalescer's drain thread:
    assembly of round N must not delay the gather of round N+1, so the
    drain thread calls ``dispatch_all`` and hands the ``FleetDispatch``
    to a finish pool.  ``assemble`` touches only host arrays already
    fetched from the device, so it is safe on any thread and needs no
    bucket lock.
    """

    def __init__(self):
        #: results already final at dispatch time: per-machine validation
        #: errors, fallback-path machines, windows-bound per-machine scores
        self.results: Dict[str, Dict[str, Any]] = {}
        #: (host outputs, bucket, [(name, slot, stack_pos, n_valid), ...])
        self._pending: List[Tuple[Dict[str, np.ndarray], Any, List[Tuple]]] = []

    @property
    def n_device_dispatches(self) -> int:
        """Stacked device dispatches gathered into this result (one per
        bucket program actually run — each staged exactly one host→device
        input transfer).  Read it BEFORE :meth:`assemble` drains the
        pending list; the backfill plane's per-chunk device-transfer
        attestation consumes it."""
        return len(self._pending)

    def assemble(self) -> Dict[str, Dict[str, np.ndarray]]:
        """Slice each machine's rows out of the stacked host outputs and
        attach its thresholds; idempotent-safe (pending entries drain)."""
        from gordo_tpu import telemetry

        pending, self._pending = self._pending, []
        for out, bucket, slots in pending:
            for name, slot, stack_pos, n_valid in slots:
                res = {
                    k: np.asarray(v[slot])[:n_valid]
                    for k, v in out.items()
                }
                if bucket.with_thresholds:
                    res["tag-anomaly-thresholds"] = bucket.thresholds_np[
                        stack_pos
                    ].copy()
                    res["total-anomaly-threshold"] = float(
                        bucket.agg_thresholds_np[stack_pos]
                    )
                # fleet-health sketch per stacked machine: the output is
                # already host numpy (device_get happened at dispatch),
                # so recording here adds one bincount and no D2H.  The
                # windows-bound and fallback paths record through their
                # own named CompiledScorers instead — results landing
                # directly in ``self.results`` never reach this loop, so
                # nothing double-counts.
                telemetry.FLEET_HEALTH.record(
                    name, res.get("total-anomaly-score")
                )
                self.results[name] = res
        return self.results

    def assemble_columnar(self) -> "codec.ColumnarResult":
        """The columnar sibling of :meth:`assemble`: keep the stacked
        host outputs STACKED and return a :class:`codec.ColumnarResult`
        of per-bucket blocks plus a (machine → block/slot/row-extent)
        map, instead of splitting into per-machine dicts.

        The blocks are zero-copy views into the dispatch outputs (a
        leading-slot prefix of a C-contiguous array is still
        contiguous), so the bulk encode path — ``encode_columnar`` over
        this result — never materializes a per-machine array.  Error
        and fallback machines (everything already final in
        ``self.results``) ride the result's ``rest`` dict with exact
        msgpack semantics.  Value parity with :meth:`assemble` is
        bitwise: both slice the same stacked host bytes.  Fleet-health
        sketches are recorded here exactly as ``assemble`` does, so the
        drift plane sees the same stream regardless of wire format.
        """
        from gordo_tpu import telemetry
        from gordo_tpu.serve import codec

        pending, self._pending = self._pending, []
        blocks: List[np.ndarray] = []
        machines: Dict[str, Dict[str, Tuple[int, int, Optional[int]]]] = {}
        scalar_blocks: set = set()
        for out, bucket, slots in pending:
            # ship only the occupied slot prefix: subset dispatches use a
            # contiguous prefix and full dispatches pad with duplicate
            # slot-0 rows, so wire waste stays bounded and no padding slot
            # carries anything a real slot doesn't
            n_slots = max(slot for _, slot, _, _ in slots) + 1
            key_block: Dict[str, int] = {}
            for k, v in out.items():
                key_block[k] = len(blocks)
                blocks.append(np.asarray(v)[:n_slots])
            thr_block = agg_block = None
            if bucket.with_thresholds:
                thr_block = len(blocks)
                blocks.append(np.asarray(bucket.thresholds_np))
                agg_block = len(blocks)
                blocks.append(np.asarray(bucket.agg_thresholds_np))
                # decodes to a python float — dtype= must not cast it
                scalar_blocks.add(agg_block)
            total_block = key_block.get("total-anomaly-score")
            for name, slot, stack_pos, n_valid in slots:
                entry: Dict[str, Tuple[int, int, Optional[int]]] = {
                    k: (b, slot, n_valid) for k, b in key_block.items()
                }
                if thr_block is not None:
                    entry["tag-anomaly-thresholds"] = (
                        thr_block, stack_pos, None,
                    )
                    entry["total-anomaly-threshold"] = (
                        agg_block, stack_pos, None,
                    )
                machines[name] = entry
                telemetry.FLEET_HEALTH.record(
                    name,
                    None if total_block is None
                    else blocks[total_block][slot][:n_valid],
                )
        return codec.ColumnarResult(
            blocks=blocks,
            machines=machines,
            scalar_blocks=scalar_blocks,
            rest=dict(self.results),
        )


class FleetScorer:
    """Serve MANY machines' anomaly scoring as stacked device programs.

    ``from_models`` buckets machines whose fused chains are structurally
    identical; ``score_all`` runs one vmapped dispatch per bucket.
    Machines that cannot fuse (or bucket alone) still work — they fall
    back to their own ``CompiledScorer`` path.
    """

    def __init__(self):
        self.buckets: List[_Bucket] = []
        self.fallbacks: Dict[str, CompiledScorer] = {}
        self.machine_bucket: Dict[str, Tuple[int, int]] = {}
        self.models: Dict[str, Any] = {}
        self._machine_scorers: Dict[str, CompiledScorer] = {}
        self.dtype: str = "float32"

    def _machine_scorer(self, name: str) -> CompiledScorer:
        if name not in self._machine_scorers:
            self._machine_scorers[name] = CompiledScorer(
                self.models[name], dtype=self.dtype, machine=name
            )
        return self._machine_scorers[name]

    @classmethod
    def from_models(
        cls,
        models: Dict[str, Any],
        mesh: Optional[Any] = None,
        pack_store: Optional[Any] = None,
        dtype: Optional[str] = None,
        prestacked_hint: Optional[Dict[str, Any]] = None,
    ) -> "FleetScorer":
        """``mesh``: optional ``("models", "data")`` fleet mesh; buckets
        shard their stacked machine axis over it so one serving dispatch
        spans every chip (single-device behavior is unchanged without it).

        ``pack_store``: the v2 :class:`gordo_tpu.artifacts.PackStore`
        the models came from, when they did.  Pack-backed machines group
        one bucket per pack and the bucket's stacked arrays ship as ONE
        whole-pack device transfer instead of a per-leaf ``jnp.stack``
        over per-machine copies — the v2 load contract.

        ``dtype``: serving precision for every bucket and fallback scorer
        (``None`` resolves ``GORDO_SERVE_DTYPE``); one fleet, one
        precision — per-machine mixing would make bulk responses depend
        on bucketing accidents.

        ``prestacked_hint``: already-stacked host arrays for the whole
        fleet (``PendingFleetBuild.prestacked``) — the builder's
        baseline-sketch call adopts them via :func:`_hint_group` instead
        of re-stacking its freshly assembled detectors' views leaf by
        leaf.  Ignored (generic stacking) on any mismatch.
        """
        self = cls()
        self.models = dict(models)
        self.dtype = (
            precision.canonical(dtype) if dtype else precision.serve_dtype()
        )
        groups: Dict[Tuple, Tuple[List[str], List[Dict]]] = {}
        for name, model in sorted(models.items()):
            chain = _extract_chain(model)
            sig = _signature(chain) if chain else None
            if sig is None:
                self.fallbacks[name] = CompiledScorer(
                    model, dtype=self.dtype, machine=name
                )
                continue
            names, chains = groups.setdefault(sig, ([], []))
            names.append(name)
            chains.append(chain)
        for names, chains in groups.values():
            prestacked = None
            if pack_store is not None:
                prestacked, names, chains = _prestack_group(
                    pack_store, names, chains
                )
            elif prestacked_hint is not None:
                prestacked, names, chains = _hint_group(
                    prestacked_hint, names, chains
                )
            bucket = _Bucket(
                names, chains, mesh=mesh, prestacked=prestacked,
                dtype=self.dtype,
            )
            idx = len(self.buckets)
            self.buckets.append(bucket)
            for pos, name in enumerate(names):
                self.machine_bucket[name] = (idx, pos)
        return self

    def delta_restack(
        self,
        models: Dict[str, Any],
        pack_store: Optional[Any],
        changed: List[str],
        mesh: Optional[Any] = None,
    ) -> "FleetScorer":
        """O(changed-machines) successor scorer after a generation flip.

        Buckets with no changed member are REUSED wholesale — same
        ``_Bucket`` object, same resident device arrays, zero transfers.
        Buckets with changed members rebuild through
        :func:`_delta_bucket`: one ``to_device`` per touched pack, device
        slices for everything else.  Every bucket (reused or rebuilt)
        keeps its dispatch shapes, so the compile plane serves all of the
        successor's programs from cache — a delta reload compiles
        nothing.

        The delta contract is checked, not assumed: membership drift
        (machines added/removed), signature drift, or geometry drift in
        any touched bucket falls back to a full :meth:`from_models`
        restack.  The old scorer is never mutated — callers keep serving
        it until they swap the returned one in.
        """
        def full() -> "FleetScorer":
            return FleetScorer.from_models(
                models, mesh=mesh, pack_store=pack_store, dtype=self.dtype
            )

        changed_set = set(changed)
        if set(models) != set(self.models):
            return full()
        if pack_store is None and changed_set:
            return full()
        known = set(self.machine_bucket) | set(self.fallbacks)
        if not changed_set <= known:
            return full()
        new = FleetScorer()
        new.dtype = self.dtype
        new.models = dict(models)
        new.fallbacks = dict(self.fallbacks)
        new._machine_scorers = {
            n: s for n, s in self._machine_scorers.items()
            if n not in changed_set
        }
        try:
            for n in changed_set & set(new.fallbacks):
                new.fallbacks[n] = CompiledScorer(
                    models[n], dtype=self.dtype, machine=n
                )
            for bucket in self.buckets:
                touched = [n for n in bucket.names if n in changed_set]
                nb = (
                    bucket if not touched
                    else _delta_bucket(
                        bucket, pack_store, touched, self.dtype
                    )
                )
                idx = len(new.buckets)
                new.buckets.append(nb)
                for pos, name in enumerate(nb.names):
                    new.machine_bucket[name] = (idx, pos)
        except _PrestackMiss:
            return full()
        return new

    @property
    def n_stacked(self) -> int:
        return sum(len(b.names) for b in self.buckets)

    def score_all(
        self, X_by_name: Dict[str, np.ndarray]
    ) -> Dict[str, Dict[str, np.ndarray]]:
        """Score every machine's rows in as few dispatches as buckets.

        Rows are padded (repeat-last) to a shared power-of-two bucket per
        program; outputs are sliced back per machine.
        """
        return self.dispatch_all(X_by_name).assemble()

    def dispatch_all(self, X_by_name: Dict[str, np.ndarray]) -> FleetDispatch:
        """The device half of :meth:`score_all`: run every stacked (and
        fallback) dispatch, defer the per-machine host-side slicing to the
        returned :class:`FleetDispatch` — callable from another thread."""
        dispatch = FleetDispatch()
        results = dispatch.results
        for bucket in self.buckets:
            wanted = [n for n in bucket.names if n in X_by_name]
            if not wanted:
                continue
            # rows a windowed model consumes: validation bound AND output
            # slicing offset (one expression — they must never diverge)
            offset_rows = (
                bucket.lookback - 1
                if bucket.mode == "ae"
                else bucket.lookback if bucket.mode == "forecast" else 0
            )
            ok_names = []
            for n in wanted:
                arr = np.asarray(X_by_name[n])
                # report malformed requests per machine; one bad machine
                # must not sink the whole stacked dispatch.  "client-error"
                # lets transports map these to 400 instead of 500.
                if arr.ndim != 2:
                    results[n] = {
                        "error": (
                            f"X must be 2-dimensional, got shape {arr.shape}"
                        ),
                        "client-error": True,
                    }
                elif arr.shape[0] <= offset_rows:
                    results[n] = {
                        "error": short_rows_message(
                            offset_rows, arr.shape[0]
                        ),
                        "client-error": True,
                    }
                elif (
                    bucket.n_features is not None
                    and arr.shape[1] != bucket.n_features
                ):
                    results[n] = {
                        "error": (
                            f"X has {arr.shape[1]} columns; model expects "
                            f"{bucket.n_features}"
                        ),
                        "client-error": True,
                    }
                else:
                    ok_names.append(n)
            wanted = ok_names
            if not wanted:
                continue
            arrays = {n: np.asarray(X_by_name[n], np.float32) for n in wanted}
            n_rows = _bucket_rows(max(a.shape[0] for a in arrays.values()))
            n_feat = next(iter(arrays.values())).shape[1]
            # A request covering only part of the bucket dispatches at the
            # SUBSET size (padded to a power of two so the jit cache stays
            # log-sized): compute and device->host transfer scale with the
            # machines actually requested, not the bucket's resident count.
            # This is what keeps coalesced rounds (~8 machines of a 64+
            # bucket) from paying full-bucket cost per dispatch.
            n_bucket = len(bucket.names)
            m_full = 1 << (len(wanted) - 1).bit_length()
            if m_full < n_bucket:
                m_eff = m_full  # subset dispatch (unsharded gather)
            else:
                # full dispatch; with a mesh the windows tensor shards
                # along the machine axis, so the PER-DEVICE bound sees
                # only each shard's machines
                m_eff = bucket.m_pad
                if bucket.mesh is not None:
                    from gordo_tpu.mesh import MODEL_AXIS

                    m_eff = -(-m_eff // bucket.mesh.shape[MODEL_AXIS])
            chunks = [wanted]
            # every per-machine windows tensor the fused program
            # materializes one-shot: the MODEL-INPUT windows of lookback
            # models (n, lookback, tags) and the smoothing windows
            # (n, smooth_window, tags) — summed, since both can be live
            win_factor = (bucket.smooth_window or 0) + (
                bucket.lookback if bucket.mode != "none" else 0
            )
            if win_factor:
                per_machine_elems = n_rows * win_factor * n_feat
                if per_machine_elems > SMOOTH_ELEMENT_BOUND:
                    # ONE machine's windows tensors alone exceed the bound
                    # — score each through its own scorer (blocked
                    # on-device median for smoothing overflow; host path
                    # for lookback overflow)
                    for n in wanted:
                        try:
                            results[n] = self._machine_scorer(
                                n
                            ).anomaly_arrays(arrays[n])
                        except Exception as exc:
                            # same per-machine isolation as the fallbacks
                            # loop: one machine's model-internal error must
                            # not 500 the whole bulk request
                            results[n] = {
                                "error": str(exc),
                                "client-error": isinstance(exc, ValueError),
                            }
                    continue
                if m_eff * per_machine_elems > SMOOTH_ELEMENT_BOUND:
                    # the windows tensor at the full dispatch size would
                    # blow device memory — split the MACHINE axis into
                    # bound-respecting subset dispatches instead of falling
                    # back to sequential per-machine scoring (which costs a
                    # full ~230ms dispatch round-trip per machine over the
                    # tunnel)
                    cap = 1 << (
                        (SMOOTH_ELEMENT_BOUND // per_machine_elems)
                        .bit_length() - 1
                    )
                    chunks = [
                        wanted[i: i + cap]
                        for i in range(0, len(wanted), cap)
                    ]
            for chunk in chunks:
                pos = [self.machine_bucket[n][1] for n in chunk]
                m_sub = 1 << (len(pos) - 1).bit_length()
                subset = m_sub < n_bucket
                # reuse the pinned stacking buffer while shapes repeat (the
                # replayed-stream case).  The lock spans stack -> dispatch
                # -> device_get: concurrent bulk requests score from
                # executor threads, and an unguarded shared buffer would
                # let one request's rows overwrite another's mid-transfer.
                # Holding it through the dispatch costs nothing — the
                # device serializes same-bucket programs anyway.
                with bucket._lock:
                    if subset:
                        # slot i holds chunk[i]'s rows; padding slots
                        # repeat slot 0 (their outputs are discarded).  idx
                        # is traced, so machine choice never recompiles —
                        # only m_sub does.
                        idx = np.asarray(
                            pos + [pos[0]] * (m_sub - len(pos)), np.int32
                        )
                        stacked = bucket.stack_buffer(
                            (m_sub, n_rows, n_feat)
                        )
                        for i, name in enumerate(chunk):
                            bucket.fill_slot(stacked, i, arrays[name])
                        stacked[len(chunk): m_sub] = stacked[0]
                        out = jax.device_get(
                            bucket.score_subset(stacked, idx)
                        )
                        slot_of = {n: i for i, n in enumerate(chunk)}
                    else:
                        # full-bucket dispatch in bucket.names order:
                        # requested machines get repeat-last row padding;
                        # absent slots (and mesh shard-padding slots past
                        # n_bucket) score a dummy copy whose output is
                        # discarded
                        spare = next(iter(arrays.values()))
                        stacked = bucket.stack_buffer(
                            (bucket.m_pad, n_rows, n_feat)
                        )
                        for i, name in enumerate(bucket.names):
                            bucket.fill_slot(stacked, i, arrays.get(name, spare))
                        stacked[n_bucket: bucket.m_pad] = stacked[0]
                        # ONE device->host transfer per output array;
                        # slicing per machine afterwards is pure numpy
                        # (per-machine indexing of device arrays would
                        # issue hundreds of tiny transfers)
                        out = jax.device_get(bucket.score(stacked))
                        # full dispatch: output slots ARE stack positions
                        slot_of = None
                # device work done (out is host numpy after device_get);
                # record the slicing plan and defer the copies to assemble()
                slots = []
                for name in chunk:
                    stack_pos = self.machine_bucket[name][1]
                    slot = stack_pos if slot_of is None else slot_of[name]
                    n_valid = arrays[name].shape[0] - offset_rows
                    slots.append((name, slot, stack_pos, n_valid))
                dispatch._pending.append((out, bucket, slots))

        for name, scorer in self.fallbacks.items():
            if name in X_by_name:
                X = np.asarray(X_by_name[name], np.float32)
                if X.ndim != 2:
                    # same clean client error as the bucketed machines get
                    results[name] = {
                        "error": (
                            f"X must be 2-dimensional, got shape {X.shape}"
                        ),
                        "client-error": True,
                    }
                    continue
                try:
                    if scorer.is_anomaly:
                        results[name] = scorer.anomaly_arrays(X)
                    else:
                        # non-anomaly model: serve its plain prediction
                        # (mirrors the client's 422 -> /prediction fallback)
                        results[name] = {"model-output": scorer.predict(X)}
                except Exception as exc:
                    # missing thresholds, short rows, model-internal errors —
                    # report per machine instead of sinking the bulk request
                    results[name] = {
                        "error": str(exc),
                        "client-error": isinstance(exc, ValueError),
                    }
        return dispatch
