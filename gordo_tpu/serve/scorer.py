"""Fused serving scorer: one jitted program per (model, shape-bucket).

Reference equivalent: the server view path
``server/views/base.py -> model.predict`` /
``views/anomaly.py -> DiffBasedAnomalyDetector.anomaly`` — there a chain of
host-side sklearn transforms, a Keras predict, and pandas frame assembly
per request.

Here the entire scoring pipeline — scaler chain, windowing, network apply,
detector scaling, |diff|, L2 total, threshold comparison — is ONE XLA
program of ``(X,) -> arrays``.  Request row counts are padded up to
power-of-two buckets so the jit cache stays small (a handful of compiles
serve any stream); padded rows are sliced off before response assembly.

The structural requirements are the same as the fleet engine's
(``parallel/anomaly.py``): pure-stats scalers + a BaseJaxEstimator.  Models
that don't match run through their own (slower, host-side) ``.anomaly`` /
``.predict`` methods transparently.
"""

from __future__ import annotations

import os
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gordo_tpu import compile as compile_plane
from gordo_tpu import telemetry
from gordo_tpu.anomaly.base import AnomalyDetectorBase
from gordo_tpu.anomaly.diff import DiffBasedAnomalyDetector, scores_fn
from gordo_tpu.models.estimator import (
    BaseJaxEstimator,
    LSTMAutoEncoder,
    LSTMForecast,
)
from gordo_tpu.ops.windows import make_windows
from gordo_tpu.pipeline import Pipeline
from gordo_tpu.serve import precision

# -- telemetry instruments (docs/observability.md "Serving dispatch") -------
#: the single-dispatch attestation pair: on the fused request path a
#: request is decode → ONE input transfer → ONE device dispatch → encode,
#: and these counters are the evidence (bench serving_precision asserts
#: deltas == request counts; divergence means host-side work crept back in)
_DISPATCHES = telemetry.counter(
    "gordo_serve_dispatches_total",
    "Device dispatches issued by the serving scorers, by program",
    labels=("program",),
)
_H2D = telemetry.counter(
    "gordo_serve_input_transfers_total",
    "Host-to-device input transfers on the serving request path, "
    "by program",
    labels=("program",),
)

#: smallest compile bucket; requests below this pad up to it.  Hardware
#: sweep (v5e via tunnel, r4): per-call latency is FLAT ~204-240ms from 32
#: to 2048 rows — dispatch round-trip dominates, padded compute is free —
#: so 256 halves jit-cache entries vs 64 at zero latency cost while keeping
#: small-request compute waste bounded on CPU/attached-device deployments.
MIN_BUCKET = 256

#: one-shot smoothing windows-tensor ceiling (elements).  Hardware probe
#: (v5e, r4): 2^27.5 still compiles, 2^28.5 kills XLA — past this, the
#: scorer switches to the blocked rolling median rather than leaving the
#: device.
SMOOTH_ONE_SHOT_BOUND = 2 ** 27
#: per-block windows-tensor size the blocked median aims for (~64MB f32)
SMOOTH_BLOCK_TARGET = 2 ** 24


def short_rows_message(offset: int, rows: int) -> str:
    """The one short-rows client-error text — the direct, bulk, and
    coalesced transports must emit identical 400 bodies."""
    return (
        f"needs more than {offset} rows (lookback window), got {rows}"
    )


def _bucket_rows(n: int) -> int:
    b = MIN_BUCKET
    while b < n:
        b *= 2
    return b


def _fused_enabled() -> bool:
    """``GORDO_SERVE_FUSED=off`` routes the diff-anomaly epilogue
    (threshold/confidence math) and request padding back through host
    numpy — the r11 request path, kept ONLY as the measured baseline for
    ``bench --stage serving_precision`` and the fused-vs-host parity pin.
    Production serving never turns this off."""
    return os.environ.get("GORDO_SERVE_FUSED", "on").strip().lower() not in (
        "off", "0", "false",
    )


def _legacy_pad(X: np.ndarray, bucket: int) -> np.ndarray:
    """The r11 host-side repeat-last pad (double copy: concatenate then
    the transfer).  Only reachable with ``GORDO_SERVE_FUSED=off``; the
    fused path writes into a pinned pad buffer instead."""
    return np.concatenate([X, np.tile(X[-1:], (bucket - X.shape[0], 1))])


def _extract_chain(model) -> Optional[Dict[str, Any]]:
    """Pull the pure pieces out of a detector/pipeline/estimator, or None."""
    detector = None
    base = model
    if isinstance(model, DiffBasedAnomalyDetector):
        detector = model
        base = model.base_estimator

    scalers: List[Tuple[type, dict]] = []
    if isinstance(base, Pipeline):
        for _, step in base.steps[:-1]:
            stats = getattr(step, "stats_", None)
            if stats is None or type(step).apply.__qualname__.startswith(
                "BaseTransform"
            ):
                return None
            scalers.append((type(step), stats))
        est = base._final
    else:
        est = base
    if not isinstance(est, BaseJaxEstimator) or est.params_ is None:
        return None
    if est.module_ is None:
        est._rebuild_module()

    if isinstance(est, LSTMForecast):
        mode, lookback = "forecast", est.lookback_window
    elif isinstance(est, LSTMAutoEncoder):
        mode, lookback = "ae", est.lookback_window
    else:
        mode, lookback = "none", 1

    chain: Dict[str, Any] = {
        "scalers": scalers,
        "module": est.module_,
        "params": est.params_,
        "mode": mode,
        "lookback": lookback,
        "detector": None,
    }
    if detector is not None:
        if detector.scaler is None or getattr(detector.scaler, "stats_", None) is None:
            return None
        chain["detector"] = {
            "scaler_cls": type(detector.scaler),
            "scaler_stats": detector.scaler.stats_,
            "feature_thresholds": detector.feature_thresholds_,
            "aggregate_threshold": detector.aggregate_threshold_,
            "require_thresholds": detector.require_thresholds,
            "window": int(detector.window or 0),
        }
    return chain


def _rolling_median(a: jnp.ndarray, window: int) -> jnp.ndarray:
    """Trailing rolling median with ``min_periods=1`` — matches the pandas
    smoothing in ``DiffBasedAnomalyDetector.anomaly`` exactly (early rows
    take the median of however many samples exist)."""
    squeeze = a.ndim == 1
    if squeeze:
        a = a[:, None]
    pad = jnp.full((window - 1,) + a.shape[1:], jnp.nan, a.dtype)
    windows = make_windows(jnp.concatenate([pad, a], axis=0), window)
    out = jnp.nanmedian(windows, axis=1)
    return out[:, 0] if squeeze else out


def _rolling_median_blocked(
    a: jnp.ndarray, window: int, block_rows: int
) -> jnp.ndarray:
    """:func:`_rolling_median` with the windows tensor materialized only
    ``block_rows`` rows at a time (``lax.map`` over row blocks, each block
    sliced with ``window - 1`` rows of preceding context).

    Bit-identical to the one-shot version; memory drops from
    ``n x window x tags`` to ``block_rows x window x tags`` per step.
    Exists because the one-shot tensor has a hard compile ceiling on TPU
    (measured r4: 2^27.5 elements OK, 2^28.5 fails XLA) — beyond it, huge
    smoothed requests previously fell off the device entirely.
    """
    squeeze = a.ndim == 1
    if squeeze:
        a = a[:, None]
    n, f = a.shape
    n_blocks = -(-n // block_rows)
    ctx = jnp.full((window - 1, f), jnp.nan, a.dtype)
    tail = jnp.full((n_blocks * block_rows - n, f), jnp.nan, a.dtype)
    buf = jnp.concatenate([ctx, a, tail], axis=0)

    def one(start):
        blk = jax.lax.dynamic_slice(
            buf, (start, 0), (block_rows + window - 1, f)
        )
        return jnp.nanmedian(make_windows(blk, window), axis=1)

    out = jax.lax.map(one, jnp.arange(n_blocks) * block_rows)
    out = out.reshape(n_blocks * block_rows, f)[:n]
    return out[:, 0] if squeeze else out


def _score_program_fn(
    module,
    scaler_classes,
    mode,
    lookback,
    det_cls,
    with_anomaly,
    smooth_window,
    dtype,
    with_confidence,
    scaler_stats,
    params,
    det_stats,
    agg_threshold,
    X,
    smooth_block=0,
):
    """(X padded to bucket) -> dict of arrays; the whole pipeline fused —
    scaler chain, windowing, network apply, detector scaling, |diff|, L2
    total, smoothing, AND the confidence epilogue — at the serving
    precision ``dtype`` (a static: it keys the compiled executable).
    Outputs always leave the program as float32, so the response schema
    is dtype-invariant; reduced precision is an internal compute matter
    gated by the fp32 parity suite."""
    Xc = precision.cast_input(X, dtype)
    scaler_stats = precision.cast_params(scaler_stats, dtype)
    params = precision.cast_params(params, dtype)
    det_stats = precision.cast_params(det_stats, dtype)
    Xs = Xc
    for cls, stats in zip(scaler_classes, scaler_stats):
        Xs = cls.apply(stats, Xs)

    if mode == "none":
        inputs = Xs
    elif mode == "ae":
        inputs = make_windows(Xs, lookback)
    else:  # forecast
        inputs = make_windows(Xs[:-1], lookback)

    pred = module.apply({"params": params}, inputs)
    out = {"model-output": pred.astype(jnp.float32)}
    if with_anomaly:
        offset = X.shape[0] - pred.shape[0]
        y_al = Xc[offset:]
        tag, total = scores_fn(det_cls, det_stats, y_al, pred)
        if smooth_window and smooth_block:
            tag = _rolling_median_blocked(tag, smooth_window, smooth_block)
            total = _rolling_median_blocked(
                total, smooth_window, smooth_block
            )
        elif smooth_window:
            tag = _rolling_median(tag, smooth_window)
            total = _rolling_median(total, smooth_window)
        tag = tag.astype(jnp.float32)
        total = total.astype(jnp.float32)
        out["tag-anomaly-scores"] = tag
        out["total-anomaly-score"] = total
        if with_confidence:
            # the diff-anomaly epilogue, fused: confidence is computed on
            # device in f32 (thresholds never quantize) — the last piece
            # of host numpy the request path used to pay per request
            out["anomaly-confidence"] = total / jnp.maximum(
                agg_threshold.astype(jnp.float32), 1e-12
            )
    return out


#: the per-machine fused serving program, owned by the compile plane: the
#: server's startup warmup AOT-compiles it per (signature, row bucket,
#: serving dtype) before the readiness flip, so the first request never
#: traces
_score_program = compile_plane.program(
    "serve.score",
    _score_program_fn,
    static_argnames=(
        "module", "scaler_classes", "mode", "lookback", "det_cls",
        "with_anomaly", "smooth_window", "dtype", "with_confidence",
        "smooth_block",
    ),
)


def _program_args(
    c: Dict[str, Any],
    X: Any,
    with_anomaly: bool,
    smooth_block: int,
    dtype: str,
    with_confidence: bool,
) -> Tuple[Tuple, Dict[str, Any]]:
    """The ONE assembly of ``_score_program``'s arguments — the dispatch
    path (``_run``) and the AOT warmup (``warm_programs``) must agree on
    every static value and pytree layout, or the warmed executable would
    never be the one a request looks up."""
    det = c["detector"]
    args = (
        c["module"],
        tuple(cls for cls, _ in c["scalers"]),
        c["mode"],
        c["lookback"],
        det["scaler_cls"] if det else None,
        bool(with_anomaly and det),
        det["window"] if (det and with_anomaly) else 0,
        dtype,
        with_confidence,
        tuple(stats for _, stats in c["scalers"]),
        c["params"],
        det["scaler_stats"] if det else None,
        # a () f32 leaf, not a python float: its signature must be
        # identical between warm (ShapeDtypeStruct-adjacent) and dispatch
        np.float32(det["aggregate_threshold"]) if with_confidence else None,
        X,
    )
    return args, {"smooth_block": smooth_block}


class CompiledScorer:
    """Callable scoring surface over one model; jitted when possible.

    ``dtype``: the serving precision this scorer dispatches at
    (``None`` resolves ``GORDO_SERVE_DTYPE`` per call — the env knob is
    live for tests and embedding callers; collections resolve once and
    pass it explicitly so a whole fleet serves one precision).

    ``machine``: the fleet machine name this scorer serves, when known
    (``ModelEntry`` and the fleet scorer's per-machine paths set it).
    With a name, every anomaly response's total-anomaly-score array
    folds into that machine's fleet-health sketch
    (:mod:`gordo_tpu.telemetry.fleet_health`) — accumulated from the
    host arrays already fetched for response encoding, so the hot path
    pays one vectorized bincount and no extra D2H.  Nameless scorers
    (ad-hoc/bench embedding) record nothing.
    """

    #: max retained pinned pad buffers (power-of-two row bucketing keeps
    #: distinct request shapes log-few; mirrors _Bucket.MAX_STACK_BUFS)
    MAX_PAD_BUFS = 4

    def __init__(
        self,
        model,
        dtype: Optional[str] = None,
        machine: Optional[str] = None,
    ):
        self.model = model
        self.chain = _extract_chain(model)
        self.is_anomaly = isinstance(model, AnomalyDetectorBase)
        self.offset = getattr(model, "offset", 0)
        self.machine = machine
        self._dtype = precision.canonical(dtype) if dtype else None
        #: pinned host pad buffers keyed by (bucket_rows, n_features),
        #: reused while request shapes repeat: padding writes ONE copy
        #: into the buffer and the transfer is the only other touch —
        #: the r11 path concatenated a fresh padded array first (two
        #: copies per request).  Guarded by _pad_lock: concurrent
        #: requests for one machine run _run from executor threads.
        self._pad_bufs: "OrderedDict[Tuple[int, int], np.ndarray]" = (
            OrderedDict()
        )
        self._pad_lock = threading.Lock()

    @property
    def fused(self) -> bool:
        return self.chain is not None

    @property
    def dtype(self) -> str:
        return self._dtype or precision.serve_dtype()

    def _pad_buffer(self, shape: Tuple[int, int]) -> np.ndarray:
        """Pinned pad buffer for ``shape`` (call with ``_pad_lock`` held)."""
        buf = self._pad_bufs.get(shape)
        if buf is None:
            buf = self._pad_bufs[shape] = np.empty(shape, np.float32)
            while len(self._pad_bufs) > self.MAX_PAD_BUFS:
                self._pad_bufs.popitem(last=False)
        else:
            self._pad_bufs.move_to_end(shape)
        return buf

    # -- fused path ----------------------------------------------------------
    def _run(
        self, X: np.ndarray, with_anomaly: bool, smooth_block: int = 0
    ) -> Dict[str, np.ndarray]:
        c = self.chain
        det = c["detector"]
        dtype = self.dtype
        fused = _fused_enabled()
        with_confidence = bool(
            with_anomaly and fused and det
            and det["feature_thresholds"] is not None
        )
        n = X.shape[0]
        bucket = _bucket_rows(n)
        if bucket != n and not fused:
            X = _legacy_pad(X, bucket)
        if bucket != n and fused:
            # single-copy repeat-last padding into the pinned buffer; the
            # lock spans fill -> transfer so a concurrent request can't
            # overwrite rows mid-copy.  jnp.array (copy=True), NOT
            # jnp.asarray: on the CPU backend asarray may ZERO-COPY ALIAS
            # the numpy buffer, and the next same-bucket request would
            # then rewrite this request's live device array after the
            # lock drops (observed as coalesced-vs-direct mismatches
            # under concurrency).  On real accelerators the H2D DMA is
            # the copy either way.  The input transfer stays f32 (the
            # client's precision); reduced-precision casts happen inside
            # the program, where they are free.
            with self._pad_lock:
                buf = self._pad_buffer((bucket, X.shape[1]))
                buf[:n] = X
                buf[n:] = X[-1:]
                _H2D.inc(1.0, "serve.score")
                Xd = jnp.array(buf, jnp.float32)
        else:
            _H2D.inc(1.0, "serve.score")
            Xd = jnp.asarray(X, jnp.float32)
        args, kw = _program_args(
            c, Xd, with_anomaly, smooth_block, dtype, with_confidence
        )
        # the ONE device dispatch of this request (attested by bench
        # serving_precision: counter delta == request count)
        _DISPATCHES.inc(1.0, "serve.score")
        out = _score_program(*args, **kw)
        n_valid = n - self.offset
        return {k: np.asarray(v)[:n_valid] for k, v in out.items()}

    def warm_programs(
        self, rows: int, n_features: int, dtype: Optional[str] = None
    ) -> List[Tuple[str, float]]:
        """AOT-compile this machine's fused program(s) for one row bucket
        — shape structs only, nothing executes.  ``dtype`` defaults to
        this scorer's serving dtype, so warmed executables are the ones
        dispatch looks up.  Returns ``[(label, compile_seconds), ...]``
        (0.0 = already compiled)."""
        if not self.fused:
            return []
        dtype = precision.canonical(dtype) if dtype else self.dtype
        X = jax.ShapeDtypeStruct((int(rows), int(n_features)), jnp.float32)
        det = self.chain["detector"]
        out: List[Tuple[str, float]] = []
        variants = [("serve.score/predict", False)]
        if self.is_anomaly and det is not None and not (
            det["feature_thresholds"] is None and det["require_thresholds"]
        ):
            variants.append(("serve.score/anomaly", True))
        for label, with_anomaly in variants:
            with_confidence = bool(
                with_anomaly and _fused_enabled() and det
                and det["feature_thresholds"] is not None
            )
            args, kw = _program_args(
                self.chain, X, with_anomaly, 0, dtype, with_confidence
            )
            out.append((label, _score_program.warm(*args, **kw)))
        return out

    def _require_rows(self, X: np.ndarray) -> None:
        """Windowed models consume ``offset`` rows; fewer input rows than
        that would slice the padded output with a NEGATIVE bound and return
        silently wrong arrays — reject as a client error instead."""
        if X.shape[0] <= self.offset:
            raise ValueError(short_rows_message(self.offset, X.shape[0]))

    def _input_windows_within_bound(self, X: np.ndarray) -> bool:
        """The fused program materializes the model-input windows tensor
        ``(n, lookback, tags)`` one-shot; past the measured compile
        ceiling there is no blocked variant (inference consumes the
        windows), so callers route such requests to the host path."""
        if self.chain["mode"] == "none":
            return True
        n_feat = max(X.shape[1], 1)
        return (
            _bucket_rows(X.shape[0]) * self.chain["lookback"] * n_feat
            <= SMOOTH_ONE_SHOT_BOUND
        )

    # -- public surface ------------------------------------------------------
    def predict(self, X) -> np.ndarray:
        X = np.asarray(X, np.float32)
        self._require_rows(X)
        if self.fused and self._input_windows_within_bound(X):
            return self._run(X, with_anomaly=False)["model-output"]
        return np.asarray(self.model.predict(X))

    def anomaly_arrays(self, X, y: Optional[np.ndarray] = None) -> Dict[str, Any]:
        """Anomaly scoring as plain arrays (no pandas on the hot path)."""
        if not self.is_anomaly:
            raise TypeError(
                f"{type(self.model).__name__} is not an anomaly detector"
            )
        X = np.asarray(X, np.float32)
        self._require_rows(X)
        use_fused = (
            self.fused
            and (y is None or y is X)
            and self._input_windows_within_bound(X)
        )
        smooth_block = 0
        if use_fused and self.chain["detector"]["window"]:
            # the one-shot smoothing path materializes an (n, window, tags)
            # windows tensor; past the measured device bound, switch to the
            # blocked rolling median (identical results, lax.map over row
            # blocks) instead of leaving the device
            det_w = self.chain["detector"]["window"]
            n_feat = max(X.shape[1], 1)
            if (
                _bucket_rows(X.shape[0]) * det_w * n_feat
                > SMOOTH_ONE_SHOT_BOUND
            ):
                smooth_block = max(
                    1, SMOOTH_BLOCK_TARGET // (det_w * n_feat)
                )
        if use_fused:
            det = self.chain["detector"]
            if det["feature_thresholds"] is None and det["require_thresholds"]:
                # same contract as DiffBasedAnomalyDetector.anomaly: refuse
                # to emit unthresholded scores.
                raise AttributeError(
                    "DiffBasedAnomalyDetector.anomaly called with "
                    "require_thresholds=True but cross_validate() has not "
                    "been run to derive thresholds"
                )
            out = self._run(X, with_anomaly=True, smooth_block=smooth_block)
            result = {
                "model-output": out["model-output"],
                "tag-anomaly-scores": out["tag-anomaly-scores"],
                "total-anomaly-score": out["total-anomaly-score"],
            }
            if det["feature_thresholds"] is not None:
                # thresholds are per-model constants: attaching them is
                # response assembly, not per-row compute — the confidence
                # SERIES rides out of the fused program already computed
                result["tag-anomaly-thresholds"] = np.asarray(
                    det["feature_thresholds"]
                )
                result["total-anomaly-threshold"] = float(
                    det["aggregate_threshold"]
                )
                if "anomaly-confidence" in out:
                    result["anomaly-confidence"] = out["anomaly-confidence"]
                else:  # GORDO_SERVE_FUSED=off: the r11 host-side epilogue
                    result["anomaly-confidence"] = result[
                        "total-anomaly-score"
                    ] / max(float(det["aggregate_threshold"]), 1e-12)
            # fleet-health sketch: fold the response's (already host-
            # resident) total scores into this machine's live window
            telemetry.FLEET_HEALTH.record(
                self.machine, result["total-anomaly-score"]
            )
            return result
        # fallback: the model's own pandas path
        frame = self.model.anomaly(X, y)
        result = {
            "model-output": frame["model-output"].to_numpy(),
            "tag-anomaly-scores": frame["tag-anomaly-scores"].to_numpy(),
            "total-anomaly-score": frame[("total-anomaly-score", "")].to_numpy(),
        }
        if ("total-anomaly-threshold", "") in frame.columns:
            result["tag-anomaly-thresholds"] = frame[
                "tag-anomaly-thresholds"
            ].to_numpy()[0]
            result["total-anomaly-threshold"] = float(
                frame[("total-anomaly-threshold", "")].iloc[0]
            )
            result["anomaly-confidence"] = frame[
                ("anomaly-confidence", "")
            ].to_numpy()
        telemetry.FLEET_HEALTH.record(
            self.machine, result["total-anomaly-score"]
        )
        return result


def compile_scorer(model, dtype: Optional[str] = None) -> CompiledScorer:
    """Build (and warm up lazily) the serving scorer for ``model``."""
    return CompiledScorer(model, dtype=dtype)
