"""Replayed-stream HTTP serving benchmark (BASELINE measurement config 5).

Reference equivalent: none shipped — SURVEY.md §7 prescribes "server under
replayed sensor stream" as the serving measurement.  Here: a real aiohttp
server on a TCP port, a client replaying a multi-machine sensor stream
against it, end-to-end sensor-samples/s out the far side — request
parsing, executor handoff, device dispatch, and the response codec all
included (the in-process scorer numbers in ``bench.py`` deliberately
exclude those, which is why both are reported).

Request bodies are pre-serialized outside the timed loop: the subject
under test is the server, not the load generator.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict, List, Optional, Sequence

import aiohttp
import numpy as np
from aiohttp import web

from gordo_tpu.serve import codec
from gordo_tpu.serve.server import API_PREFIX, ModelCollection, build_app


def _make_stream(
    collection: ModelCollection,
    names: Sequence[str],
    rows: int,
    n_rounds: int,
    seed: int = 0,
) -> Dict[str, List[np.ndarray]]:
    """Per-machine, per-round synthetic sensor chunks (distinct per round —
    a replay of identical bytes would let caches lie)."""
    rng = np.random.default_rng(seed)
    return {
        name: [
            rng.standard_normal(
                (rows, len(collection.get(name).tags))
            ).astype(np.float32)
            for _ in range(n_rounds)
        ]
        for name in names
    }


async def _replay(
    collection: ModelCollection,
    mode: str,
    wire: str,
    n_rounds: int,
    rows: int,
    parallelism: int,
    machines: Optional[Sequence[str]],
    timeout_s: float,
    coalesce_window_ms: float = 0.0,
    coalesce_min_concurrency: int = 2,
) -> Dict[str, Any]:
    runner = web.AppRunner(
        build_app(
            collection,
            coalesce_window_ms=coalesce_window_ms,
            coalesce_min_concurrency=coalesce_min_concurrency,
        )
    )
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = runner.addresses[0][1]
    base = f"http://127.0.0.1:{port}{API_PREFIX}/{collection.project}"
    names = list(machines) if machines else sorted(collection.entries)
    # n_rounds + 1: round 0 is warm-up only — re-timing its byte-identical
    # bodies would hand caches a free third of the measurement
    stream = _make_stream(collection, names, rows, n_rounds + 1)
    n_samples_round = sum(arrs[0].size for arrs in stream.values())

    if wire == "msgpack":
        content_type = codec.MSGPACK_CONTENT_TYPE
        headers = {
            "Content-Type": content_type,
            "Accept": content_type,
        }
        enc = codec.packb
    else:
        content_type = "application/json"
        headers = {"Content-Type": content_type}
        enc = lambda obj: json.dumps(  # noqa: E731
            {
                k: ({m: a.tolist() for m, a in v.items()}
                    if isinstance(v, dict) else v.tolist())
                for k, v in obj.items()
            }
        ).encode()

    # pre-serialized request bodies, one per (round, request)
    if mode == "bulk":
        bodies = [
            [(f"{base}/_bulk/anomaly/prediction",
              enc({"X": {m: stream[m][r] for m in names}}))]
            for r in range(n_rounds + 1)
        ]
    else:
        bodies = [
            [(f"{base}/{m}/anomaly/prediction", enc({"X": stream[m][r]}))
             for m in names]
            for r in range(n_rounds + 1)
        ]

    errors: List[str] = []
    client_timeout = aiohttp.ClientTimeout(total=timeout_s)
    async with aiohttp.ClientSession(timeout=client_timeout) as session:
        sem = asyncio.Semaphore(parallelism)

        latencies: List[float] = []

        async def post(url: str, body: bytes) -> int:
            t_req = time.perf_counter()  # before the semaphore: queueing
            # behind in-flight peers is part of what a real client sees
            async with sem:
                async with session.post(
                    url, data=body, headers=headers
                ) as resp:
                    raw = await resp.read()
                    latencies.append(time.perf_counter() - t_req)
                    if resp.status != 200:
                        errors.append(
                            f"{resp.status}: {raw[:200]!r}"
                        )
                    return len(raw)

        # warm-up round: jit compiles, scorer stacking, codec caches
        await asyncio.gather(*(post(u, b) for u, b in bodies[0]))
        if errors:
            raise RuntimeError(f"Replay warm-up failed: {errors[:3]}")
        latencies.clear()  # warm-up requests are not part of the measurement

        t0 = time.perf_counter()
        response_bytes = 0
        for round_bodies in bodies[1:]:
            sizes = await asyncio.gather(
                *(post(u, b) for u, b in round_bodies)
            )
            response_bytes += sum(sizes)
        dt = time.perf_counter() - t0
    await runner.cleanup()
    if errors:
        raise RuntimeError(f"Replay had {len(errors)} errors: {errors[:3]}")
    p50, p99 = (
        np.percentile(latencies, [50, 99]) if latencies else (float("nan"),) * 2
    )
    return {
        "mode": mode,
        "wire": wire,
        "n_machines": len(names),
        "rows_per_request": rows,
        "n_rounds": n_rounds,
        "seconds": dt,
        "samples_per_sec": n_rounds * n_samples_round / dt,
        "response_mb_per_sec": response_bytes / dt / 1e6,
        # under-load request latency, timed from submission (queueing
        # behind the in-flight window included — what a client experiences).
        # latency_n is the sample count: with few requests (bulk mode runs
        # one per round) the "p99" is really a near-max — read it with n.
        "latency_n": len(latencies),
        "latency_p50_ms": float(p50 * 1e3),
        "latency_p99_ms": float(p99 * 1e3),
    }


def replay_bench(
    collection: ModelCollection,
    mode: str = "bulk",
    wire: str = "json",
    n_rounds: int = 5,
    rows: int = 2048,
    parallelism: int = 8,
    machines: Optional[Sequence[str]] = None,
    timeout_s: float = 600.0,
    coalesce_window_ms: float = 0.0,
    coalesce_min_concurrency: int = 2,
) -> Dict[str, Any]:
    """Measure end-to-end HTTP anomaly-scoring throughput.

    ``mode``: ``"bulk"`` (one ``_bulk`` request per round carrying every
    machine's chunk) or ``"single"`` (one request per machine per round,
    ``parallelism`` in flight).  ``wire``: ``"json"`` or ``"msgpack"``.
    ``coalesce_window_ms``: enable the server's cross-request coalescer
    (requests below ``coalesce_min_concurrency`` in flight bypass it).
    """
    return asyncio.run(
        _replay(
            collection, mode, wire, n_rounds, rows, parallelism, machines,
            timeout_s, coalesce_window_ms, coalesce_min_concurrency,
        )
    )
