"""Replayed-stream HTTP serving benchmark (BASELINE measurement config 5).

Reference equivalent: none shipped — SURVEY.md §7 prescribes "server under
replayed sensor stream" as the serving measurement.  Here: a real aiohttp
server on a TCP port, a client replaying a multi-machine sensor stream
against it, end-to-end sensor-samples/s out the far side — request
parsing, executor handoff, device dispatch, and the response codec all
included (the in-process scorer numbers in ``bench.py`` deliberately
exclude those, which is why both are reported).

Two load models, because they answer different questions:

- **Closed loop** (default): ``parallelism`` requests in flight, each new
  request fired the moment one completes.  Measures saturation
  throughput; its latency percentiles are saturation artifacts (queueing
  behind the in-flight window) — honest about capacity, useless for SLOs.
- **Open loop** (``arrival_rate_hz > 0``): requests fire on a fixed
  schedule regardless of completions, the way real independent clients
  arrive.  Latency is measured from each request's SCHEDULED start, so a
  server falling behind accumulates the backlog into its tail — the p99
  an SLO would actually use.  :func:`openloop_bench` runs the standard
  protocol: measure saturation closed-loop, then report p50/p99 at fixed
  fractions (0.5×, 0.8×) of it.

Request bodies are pre-serialized outside the timed loop: the subject
under test is the server, not the load generator.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict, List, Optional, Sequence

import aiohttp
import numpy as np
from aiohttp import web

from gordo_tpu.serve import codec
from gordo_tpu.serve.server import API_PREFIX, ModelCollection, build_app


def _make_stream(
    collection: ModelCollection,
    names: Sequence[str],
    rows: int,
    n_rounds: int,
    seed: int = 0,
) -> Dict[str, List[np.ndarray]]:
    """Per-machine, per-round synthetic sensor chunks (distinct per round —
    a replay of identical bytes would let caches lie)."""
    rng = np.random.default_rng(seed)
    return {
        name: [
            rng.standard_normal(
                (rows, len(collection.get(name).tags))
            ).astype(np.float32)
            for _ in range(n_rounds)
        ]
        for name in names
    }


async def _replay(
    collection: ModelCollection,
    mode: str,
    wire: str,
    n_rounds: int,
    rows: int,
    parallelism: int,
    machines: Optional[Sequence[str]],
    timeout_s: float,
    coalesce_window_ms: float = 0.0,
    coalesce_min_concurrency: int = 2,
    coalesce_knee_batch: int = 0,
    arrival_rate_hz: float = 0.0,
    openloop_duration_s: float = 5.0,
) -> Dict[str, Any]:
    app = build_app(
        collection,
        coalesce_window_ms=coalesce_window_ms,
        coalesce_min_concurrency=coalesce_min_concurrency,
        coalesce_knee_batch=coalesce_knee_batch,
    )
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    port = runner.addresses[0][1]
    base = f"http://127.0.0.1:{port}{API_PREFIX}/{collection.project}"
    names = list(machines) if machines else sorted(collection.entries)
    # n_rounds + 1: round 0 is warm-up only — re-timing its byte-identical
    # bodies would hand caches a free third of the measurement
    stream = _make_stream(collection, names, rows, n_rounds + 1)
    n_samples_round = sum(arrs[0].size for arrs in stream.values())

    if wire == "msgpack":
        content_type = codec.MSGPACK_CONTENT_TYPE
        headers = {
            "Content-Type": content_type,
            "Accept": content_type,
        }
        enc = codec.packb
    else:
        content_type = "application/json"
        headers = {"Content-Type": content_type}
        enc = lambda obj: json.dumps(  # noqa: E731
            {
                k: ({m: a.tolist() for m, a in v.items()}
                    if isinstance(v, dict) else v.tolist())
                for k, v in obj.items()
            }
        ).encode()

    # pre-serialized request bodies, one (url, body, n_samples) per
    # (round, request) — the sample count rides along so the open-loop
    # schedule can account for what it actually sent
    if mode == "bulk":
        bodies = [
            [(f"{base}/_bulk/anomaly/prediction",
              enc({"X": {m: stream[m][r] for m in names}}),
              n_samples_round)]
            for r in range(n_rounds + 1)
        ]
    else:
        bodies = [
            [(f"{base}/{m}/anomaly/prediction", enc({"X": stream[m][r]}),
              stream[m][r].size)
             for m in names]
            for r in range(n_rounds + 1)
        ]

    errors: List[str] = []
    client_timeout = aiohttp.ClientTimeout(total=timeout_s)
    async with aiohttp.ClientSession(timeout=client_timeout) as session:
        sem = asyncio.Semaphore(parallelism)

        latencies: List[float] = []

        async def post(
            url: str, body: bytes, t_sched: Optional[float] = None
        ) -> int:
            """One measured request.  Closed loop: latency from submission
            (queueing behind the in-flight window included).  Open loop
            (``t_sched``): latency from the SCHEDULED start — when the
            server falls behind the arrival schedule, the backlog lands in
            the tail instead of silently throttling the load."""
            t_req = time.perf_counter() if t_sched is None else t_sched
            if t_sched is None:
                async with sem:
                    async with session.post(
                        url, data=body, headers=headers
                    ) as resp:
                        raw = await resp.read()
            else:  # open loop: no semaphore — arrivals don't wait for peers
                async with session.post(
                    url, data=body, headers=headers
                ) as resp:
                    raw = await resp.read()
            latencies.append(time.perf_counter() - t_req)
            if resp.status != 200:
                errors.append(f"{resp.status}: {raw[:200]!r}")
            return len(raw)

        # warm-up round: jit compiles, scorer stacking, codec caches
        await asyncio.gather(*(post(u, b) for u, b, _ in bodies[0]))
        if errors:
            raise RuntimeError(f"Replay warm-up failed: {errors[:3]}")
        if coalesce_window_ms > 0:
            # warm the coalescer's knee estimate like production warmup
            # (`run-server --warmup`) would — otherwise the sweep runs
            # lazily INSIDE the measured rounds, contending with them,
            # and the batch cap stays at its pre-knee bound throughout.
            # Counters reset afterwards so the reported stats attest the
            # MEASURED window only (e.g. "routed 100% direct" is visible
            # when the sweep found no amortization).
            from gordo_tpu.serve.server import COALESCER_KEY

            coalescer = app[COALESCER_KEY]
            await asyncio.get_running_loop().run_in_executor(
                None, lambda: coalescer.ensure_knee(rows)
            )
            coalescer.reset_stats()
        latencies.clear()  # warm-up requests are not part of the measurement

        response_bytes = 0
        if arrival_rate_hz > 0:
            # ---- open loop: fixed-rate schedule over the measured bodies
            flat = [req for rnd in bodies[1:] for req in rnd]
            n_requests = max(
                int(arrival_rate_hz * openloop_duration_s), 20
            )
            schedule = [flat[i % len(flat)] for i in range(n_requests)]
            total_samples = sum(n for _, _, n in schedule)
            tasks = []
            t0 = time.perf_counter()
            for i, (u, b, _) in enumerate(schedule):
                target = t0 + i / arrival_rate_hz
                delay = target - time.perf_counter()
                if delay > 0:
                    await asyncio.sleep(delay)
                tasks.append(
                    asyncio.create_task(post(u, b, t_sched=target))
                )
            sizes = await asyncio.gather(*tasks)
            dt = time.perf_counter() - t0
            response_bytes = sum(sizes)
            n_measured = n_requests
        else:
            # ---- closed loop: rounds at fixed in-flight parallelism
            total_samples = n_rounds * n_samples_round
            t0 = time.perf_counter()
            for round_bodies in bodies[1:]:
                sizes = await asyncio.gather(
                    *(post(u, b) for u, b, _ in round_bodies)
                )
                response_bytes += sum(sizes)
            dt = time.perf_counter() - t0
            n_measured = sum(len(rnd) for rnd in bodies[1:])

        # scrape the server's own /metrics over the same TCP surface:
        # every replay run doubles as the assertion that the instrumented
        # server emits parseable Prometheus text under load (the tier-1
        # lane's scrape check rides tests/test_server.py's replay smoke)
        async with session.get(
            f"http://127.0.0.1:{port}/metrics"
        ) as resp:
            metrics_text = await resp.text()
        metrics_scrape = {
            "status": resp.status,
            "families": metrics_text.count("# TYPE "),
            "has_request_histogram": (
                "gordo_server_request_seconds_bucket" in metrics_text
            ),
            "has_coalescer_gauges": (
                "gordo_coalesce_batch_cap" in metrics_text
            ),
        }
    coalescer_stats = None
    if coalesce_window_ms > 0:
        from gordo_tpu.serve import coalesce as coalesce_mod
        from gordo_tpu.serve.server import COALESCER_KEY

        coalescer_stats = coalesce_mod.stats(app[COALESCER_KEY])
    await runner.cleanup()
    if errors:
        raise RuntimeError(f"Replay had {len(errors)} errors: {errors[:3]}")
    p50, p99 = (
        np.percentile(latencies, [50, 99]) if latencies else (float("nan"),) * 2
    )
    out = {
        "mode": mode,
        "wire": wire,
        "n_machines": len(names),
        "rows_per_request": rows,
        "n_rounds": n_rounds,
        "seconds": dt,
        "samples_per_sec": total_samples / dt,
        "requests_per_sec": n_measured / dt,
        "response_mb_per_sec": response_bytes / dt / 1e6,
        # request latency, timed from submission (closed loop: queueing
        # behind the in-flight window included) or from the scheduled
        # arrival (open loop: schedule backlog included — what an
        # independent client experiences at that rate).
        # latency_n is the sample count: with few requests (bulk mode runs
        # one per round) the "p99" is really a near-max — read it with n.
        "latency_n": len(latencies),
        "latency_p50_ms": float(p50 * 1e3),
        "latency_p99_ms": float(p99 * 1e3),
        # how the in-run /metrics scrape went (status, family count, and
        # whether the serving instruments were present in the exposition)
        "metrics_scrape": metrics_scrape,
    }
    if arrival_rate_hz > 0:
        out["open_loop"] = True
        out["arrival_rate_hz"] = float(arrival_rate_hz)
        out["n_requests"] = n_measured
    if coalescer_stats is not None:
        # how the adaptive policy actually behaved during the run
        # (mean_batch, batch_cap/knee, standdowns, queue_full_bypassed)
        out["coalescer"] = coalescer_stats
    return out


def replay_bench(
    collection: ModelCollection,
    mode: str = "bulk",
    wire: str = "json",
    n_rounds: int = 5,
    rows: int = 2048,
    parallelism: int = 8,
    machines: Optional[Sequence[str]] = None,
    timeout_s: float = 600.0,
    coalesce_window_ms: float = 0.0,
    coalesce_min_concurrency: int = 2,
    coalesce_knee_batch: int = 0,
    arrival_rate_hz: float = 0.0,
    openloop_duration_s: float = 5.0,
) -> Dict[str, Any]:
    """Measure end-to-end HTTP anomaly-scoring throughput.

    ``mode``: ``"bulk"`` (one ``_bulk`` request per round carrying every
    machine's chunk) or ``"single"`` (one request per machine per round,
    ``parallelism`` in flight).  ``wire``: ``"json"`` or ``"msgpack"``.
    ``coalesce_window_ms``: enable the server's cross-request coalescer
    (requests below ``coalesce_min_concurrency`` in flight bypass it;
    ``coalesce_knee_batch`` pins its dispatch cap, 0 = auto).
    ``arrival_rate_hz > 0``: OPEN-LOOP mode — fire requests on a fixed
    schedule for ``openloop_duration_s`` (cycling the pre-serialized
    bodies) instead of closed-loop rounds; latency percentiles are then
    measured from scheduled arrival times.
    """
    return asyncio.run(
        _replay(
            collection, mode, wire, n_rounds, rows, parallelism, machines,
            timeout_s, coalesce_window_ms, coalesce_min_concurrency,
            coalesce_knee_batch, arrival_rate_hz, openloop_duration_s,
        )
    )


def openloop_bench(
    collection: ModelCollection,
    mode: str = "bulk",
    wire: str = "msgpack",
    rows: int = 2048,
    machines: Optional[Sequence[str]] = None,
    parallelism: int = 8,
    sat_rounds: int = 3,
    fractions: Sequence[float] = (0.5, 0.8),
    duration_s: float = 5.0,
    timeout_s: float = 600.0,
    coalesce_window_ms: float = 0.0,
    coalesce_min_concurrency: int = 2,
    coalesce_knee_batch: int = 0,
) -> Dict[str, Any]:
    """Open-loop latency protocol: measure saturation closed-loop, then
    p50/p99 at fixed fractions of it.

    Returns ``saturation_requests_per_sec`` plus one entry per fraction
    under ``points`` (keys like ``"0.5x"``, ``"0.8x"``) carrying
    ``latency_p50_ms`` / ``latency_p99_ms`` / ``latency_n`` at that
    arrival rate.  Each run spins its own server; the jit/program caches
    are process-wide, so the saturation run doubles as warmup.
    """
    common = dict(
        mode=mode, wire=wire, rows=rows, machines=machines,
        timeout_s=timeout_s, coalesce_window_ms=coalesce_window_ms,
        coalesce_min_concurrency=coalesce_min_concurrency,
        coalesce_knee_batch=coalesce_knee_batch,
    )
    sat = replay_bench(
        collection, n_rounds=sat_rounds, parallelism=parallelism, **common
    )
    sat_rps = sat["requests_per_sec"]
    out: Dict[str, Any] = {
        "mode": mode,
        "wire": wire,
        "coalesced": coalesce_window_ms > 0,
        "saturation_requests_per_sec": sat_rps,
        "saturation_samples_per_sec": sat["samples_per_sec"],
        "saturation_parallelism": parallelism,
        "points": {},
    }
    for frac in fractions:
        res = replay_bench(
            collection,
            n_rounds=sat_rounds,
            parallelism=parallelism,
            arrival_rate_hz=frac * sat_rps,
            openloop_duration_s=duration_s,
            **common,
        )
        out["points"][f"{frac:g}x"] = {
            "arrival_rate_hz": res["arrival_rate_hz"],
            "latency_p50_ms": res["latency_p50_ms"],
            "latency_p99_ms": res["latency_p99_ms"],
            "latency_n": res["latency_n"],
            "samples_per_sec": res["samples_per_sec"],
        }
    return out
