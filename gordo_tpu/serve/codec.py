"""Response codecs for the serving hot path.

Reference equivalent: ``flask.jsonify`` over ``ndarray.tolist()`` dicts
(``server/views/base.py``).  Measured on this image, that path encodes
~1.6M floats/s — at TPU scoring rates (~3M sensor-samples/s stacked, each
emitting 2+ floats) the JSON codec becomes the serving ceiling.  Two
replacements, both preserving the response schema:

- :func:`dumps_bytes` — JSON with ndarray leaves encoded by the C
  ``fastjson`` kernel (``gordo_tpu/_native``); non-array values go through
  stdlib json.  Wire-compatible with the old output (same schema; float
  text is shortest-round-trip per dtype rather than repr-of-double).
- :func:`packb` / :func:`unpackb` — msgpack with ndarray leaves as raw
  little-endian buffers (memcpy speed).  Opt-in via the
  ``Accept: application/x-msgpack`` request header; the bundled client
  uses it for per-machine scoring.
- :func:`encode_columnar` / :func:`decode_columnar` — the ``GSB1``
  columnar block format for BULK responses.  BENCH_r18 measured the
  bulk ceiling at ~35x below the raw wire floor, lost to per-machine
  dict splitting, ``tobytes()`` copies, and eager frame construction;
  this codec ships the stacked dispatch output as one contiguous
  little-endian buffer per (bucket, column kind) plus a JSON header of
  per-machine (block, slot, row-extent) entries, so the encode side
  never splits and the decode side returns zero-copy ``np.frombuffer``
  views.  Opt-in via ``Accept: application/x-gordo-columnar``; servers
  that predate it simply match the msgpack fallback listed after it.
"""

from __future__ import annotations

import ctypes
import dataclasses
import json
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

import numpy as np

from gordo_tpu._native import load_fastjson

MSGPACK_CONTENT_TYPE = "application/x-msgpack"
COLUMNAR_CONTENT_TYPE = "application/x-gordo-columnar"

#: GSB1 = "Gordo Serving Blocks v1" (the serving sibling of the score
#: archive's GSA1 segment format).
_COLUMNAR_MAGIC = b"GSB1"


class UnsupportedWireDtype(ValueError):
    """A request asked for (or carried) an array dtype the wire format
    does not speak.  The server maps this to HTTP 415 — it is a media
    negotiation failure, not a malformed payload (400) and emphatically
    not a server error (500)."""


def _named_wire_dtypes() -> dict:
    """Canonical wire-dtype names → numpy dtypes.  bfloat16 has no
    unambiguous ``dtype.str`` (numpy renders it ``<V2``), so the wire
    names it explicitly; the rest use their standard numpy spellings."""
    import ml_dtypes

    return {
        "float16": np.dtype(np.float16),
        "float32": np.dtype(np.float32),
        "float64": np.dtype(np.float64),
        "bfloat16": np.dtype(ml_dtypes.bfloat16),
    }


def wire_np_dtype(name: str) -> np.dtype:
    """Resolve a wire dtype string (a negotiate ``dtype=`` parameter or a
    msgpack ``__nd__`` header) to a numpy dtype; raises
    :class:`UnsupportedWireDtype` for anything outside the supported set
    — float16/32/64 + bfloat16 on the float side, standard ints/bools as
    auxiliary payload."""
    named = _named_wire_dtypes()
    if name in named:
        return named[name]
    try:
        dt = np.dtype(name)
    except TypeError:
        raise UnsupportedWireDtype(
            f"unsupported wire dtype {name!r}; supported: "
            f"{', '.join(sorted(named))} and standard integer/bool dtypes"
        )
    if dt.kind in "fiub" and dt.itemsize <= 8:
        return dt
    raise UnsupportedWireDtype(
        f"unsupported wire dtype {name!r}; supported: "
        f"{', '.join(sorted(named))} and standard integer/bool dtypes"
    )


def _accept_wire_dtype(accept: str) -> Optional[np.dtype]:
    """Extract a ``dtype=...`` media-type parameter from an Accept header
    (e.g. ``application/x-msgpack;dtype=bfloat16``): the client's asked-for
    float precision on the wire.  Unknown names raise (→ 415)."""
    for media_range in accept.split(","):
        parts = [p.strip() for p in media_range.split(";")]
        for param in parts[1:]:
            key, _, value = param.partition("=")
            if key.strip().lower() == "dtype":
                return wire_np_dtype(value.strip().strip('"').lower())
    return None


def _is_float_leaf(dt: np.dtype) -> bool:
    """True for dtypes the ``dtype=`` negotiation casts: numpy floats
    plus bfloat16, whose kind is ``'V'`` so ``kind == 'f'`` misses it."""
    return dt.kind == "f" or dt.name == "bfloat16"


def _cast_float_arrays(obj: Any, dt: np.dtype) -> Any:
    """Recursively cast float ndarray leaves of a response object to the
    negotiated wire dtype (bf16 halves bulk response bytes; values are
    rounded exactly as the dtype dictates — the client opted in).  A
    leaf already at the negotiated dtype is returned as-is: ``astype``
    always copies, and on the bulk path that no-op copy is a full extra
    pass over the response."""
    if isinstance(obj, np.ndarray):
        if _is_float_leaf(obj.dtype) and obj.dtype != dt:
            return obj.astype(dt)
        return obj
    if isinstance(obj, dict):
        return {k: _cast_float_arrays(v, dt) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_cast_float_arrays(v, dt) for v in obj)
    return obj


def wants_columnar(accept: Optional[str]) -> bool:
    """True when the Accept header lists the GSB1 columnar media type.
    The bulk route checks this BEFORE dispatch so it can keep the
    stacked output stacked (``assemble_columnar``) instead of splitting
    per machine and re-gluing at encode time."""
    return COLUMNAR_CONTENT_TYPE in (accept or "")


def negotiate(accept: Optional[str]) -> Tuple[Callable[[Any], bytes], str]:
    """Pick the response encoder for an ``Accept`` header value: the
    GSB1 columnar block codec when the client lists it (highest
    precedence — a bulk client sends ``application/x-gordo-columnar,
    application/x-msgpack`` so old servers fall back), msgpack when the
    client asks for it, JSON (native-kernel ndarray leaves) otherwise;
    an optional ``dtype=`` media parameter
    (``application/x-msgpack;dtype=bfloat16``) casts float array leaves
    to that wire precision before encoding — unknown dtype names raise
    :class:`UnsupportedWireDtype` (the server's 415).  The ONE
    content-negotiation rule every response path (server handlers, the
    coalescer's pre-encoded results, benches) must share — divergence
    would make the same request encode differently depending on which
    path served it."""
    accept = accept or ""
    wire_dt = _accept_wire_dtype(accept)
    if COLUMNAR_CONTENT_TYPE in accept:
        return (
            lambda obj: encode_columnar(obj, wire_dt)
        ), COLUMNAR_CONTENT_TYPE
    base: Callable[[Any], bytes]
    if MSGPACK_CONTENT_TYPE in accept:
        base, content_type = packb, MSGPACK_CONTENT_TYPE
    else:
        base, content_type = dumps_bytes, "application/json"
    if wire_dt is None:
        return base, content_type
    return (lambda obj: base(_cast_float_arrays(obj, wire_dt))), content_type

try:
    import msgpack
except ImportError:  # pragma: no cover - msgpack is in the image
    msgpack = None


# ---------------------------------------------------------------------------
# JSON
# ---------------------------------------------------------------------------

def _encode_array_native(a: np.ndarray) -> Optional[bytes]:
    lib = load_fastjson()
    if lib is None or a.ndim not in (1, 2):
        return None
    if a.dtype == np.float32:
        fn, ctype = lib.fj_encode_f32, ctypes.c_float
    elif a.dtype == np.float64:
        fn, ctype = lib.fj_encode_f64, ctypes.c_double
    else:
        return None
    a = np.ascontiguousarray(a)
    rows = a.shape[0]
    cols = a.shape[1] if a.ndim == 2 else 0
    if a.ndim == 2 and cols == 0:
        return None  # zero-width 2-D: let json.dumps produce [[], [], ...]
    cap = a.size * 26 + rows * 2 + 16
    buf = ctypes.create_string_buffer(cap)
    n = fn(a.ctypes.data_as(ctypes.POINTER(ctype)), rows, cols, buf)
    return ctypes.string_at(buf, n)


def _encode_array(a: np.ndarray) -> bytes:
    if (
        a.dtype.kind == "f" and a.dtype.itemsize == 2
    ) or a.dtype.name == "bfloat16":
        # half-precision leaves (f16, bf16): JSON is dtype-less text, and
        # the widening to f32 is exact, so ride the native f32 kernel
        # instead of the slow tolist fallback
        a = a.astype(np.float32)
    out = _encode_array_native(a)
    if out is not None:
        return out
    return json.dumps(a.tolist()).encode()


def _enc(obj: Any, parts: List[bytes]) -> None:
    if isinstance(obj, ColumnarResult):
        _enc(obj.split(), parts)  # JSON fallback: per-machine dicts
    elif isinstance(obj, np.ndarray):
        parts.append(_encode_array(obj))
    elif isinstance(obj, dict):
        parts.append(b"{")
        first = True
        for k, v in obj.items():
            if not first:
                parts.append(b",")
            first = False
            parts.append(json.dumps(str(k)).encode())
            parts.append(b":")
            _enc(v, parts)
        parts.append(b"}")
    elif isinstance(obj, (list, tuple)):
        parts.append(b"[")
        first = True
        for v in obj:
            if not first:
                parts.append(b",")
            first = False
            _enc(v, parts)
        parts.append(b"]")
    elif isinstance(obj, np.generic):  # numpy scalar
        parts.append(json.dumps(obj.item()).encode())
    else:
        parts.append(json.dumps(obj, default=str).encode())


def dumps_bytes(obj: Any) -> bytes:
    """JSON-encode a response object; ndarray leaves ride the C kernel."""
    parts: List[bytes] = []
    _enc(obj, parts)
    return b"".join(parts)


# ---------------------------------------------------------------------------
# msgpack
# ---------------------------------------------------------------------------

#: below this, ``tobytes()`` is cheaper than buffer-protocol setup
_MEMVIEW_MIN_NBYTES = 256


def _array_wire_buffer(o: np.ndarray) -> Any:
    """The raw little-endian bytes of a contiguous array, WITHOUT the
    ``tobytes()`` copy when the array is large: a ``memoryview`` over
    the array's own buffer (msgpack packs any buffer-protocol object as
    bin, and the view keeps the array alive until the pack finishes)."""
    if o.ndim >= 1 and o.nbytes >= _MEMVIEW_MIN_NBYTES:
        try:
            return memoryview(o).cast("B")
        except (TypeError, ValueError):
            # bf16 (dtype kind 'V') doesn't export the buffer protocol;
            # a uint8 reinterpretation of the same memory does
            return memoryview(o.view(np.uint8)).cast("B")
    return o.tobytes()


def _msgpack_default(o: Any) -> Any:
    if isinstance(o, np.ndarray):
        o = np.ascontiguousarray(o)
        if o.dtype.byteorder == ">":  # wire format is little-endian
            o = o.astype(o.dtype.newbyteorder("<"))
        # bfloat16 has no unambiguous dtype.str ('<V2'); name it on the
        # wire so the decode side doesn't have to guess
        name = (
            "bfloat16" if o.dtype.name == "bfloat16" else o.dtype.str
        )
        return {
            "__nd__": True,
            "dtype": name,
            "shape": list(o.shape),
            "data": _array_wire_buffer(o),
        }
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, ColumnarResult):
        # a columnar payload that fell through to msgpack (e.g. a probe
        # without the columnar Accept) degrades to per-machine dicts
        # rather than stringifying
        return o.split()
    return str(o)


def _msgpack_hook(d: dict) -> Any:
    if d.get("__nd__"):
        # wire_np_dtype validates: an unknown or disallowed dtype string
        # raises UnsupportedWireDtype → the server's 415, not a 500 from
        # numpy choking on an alien dtype mid-request
        return np.frombuffer(
            d["data"], dtype=wire_np_dtype(str(d["dtype"]))
        ).reshape(d["shape"])
    return d


def packb(obj: Any) -> bytes:
    """msgpack-encode a response; ndarray leaves as raw buffers."""
    if msgpack is None:
        raise RuntimeError("msgpack is not available")
    return msgpack.packb(obj, default=_msgpack_default, use_bin_type=True)


def unpackb(data: bytes) -> Any:
    if msgpack is None:
        raise RuntimeError("msgpack is not available")
    return msgpack.unpackb(data, object_hook=_msgpack_hook, raw=False)


# ---------------------------------------------------------------------------
# GSB1 columnar blocks (bulk responses)
# ---------------------------------------------------------------------------
#
# Wire layout::
#
#   b"GSB1" | u32-LE header-length | header JSON | rest msgpack | blocks...
#
# The header carries the block table ({dtype, shape, nbytes}; blocks are
# laid out back-to-back in table order) and the machine map
# ({name: {response-key: [block, index, rows-or-null]}}).  Decoding a
# machine entry is ``blocks[block][index]``, sliced ``[:rows]`` when rows
# is set (the machine's valid row extent inside a padded bucket slot) and
# collapsed to a python float when the indexed view is 0-d.  ``rest`` is
# an ordinary msgpack blob holding everything that is NOT stacked — error
# and fallback machines, per-machine time-column partials, top-level
# scalars — so every odd path keeps exact msgpack semantics for free.


@dataclasses.dataclass
class ColumnarResult:
    """A bulk scoring result still in stacked (columnar) form.

    Produced by ``FleetDispatch.assemble_columnar``: ``blocks`` are the
    already-stacked per-(bucket, column-kind) arrays straight from the
    device dispatch (plus the bucket threshold stacks), ``machines``
    maps each machine name to ``{response-key: (block, index, rows)}``
    extents into them, and ``rest`` holds the non-stacked remainder
    (fallback/error machines, time-column partials) as ordinary
    per-machine dicts.  ``scalar_blocks`` marks blocks whose entries
    decode to python floats (today: the aggregate-threshold stack) —
    the ``dtype=`` negotiation must NOT cast those, because the msgpack
    path ships them as dtype-less python floats.
    """

    blocks: List[np.ndarray]
    machines: Dict[str, Dict[str, Tuple[int, int, Optional[int]]]]
    scalar_blocks: Set[int] = dataclasses.field(default_factory=set)
    rest: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def rows(self, name: str) -> Optional[int]:
        """The machine's valid row extent, or None if not stacked."""
        entry = self.machines.get(name)
        if not entry:
            return None
        for _, _, rows in entry.values():
            if rows is not None:
                return rows
        return None

    def split(self) -> Dict[str, Any]:
        """Materialize the per-machine dict-of-arrays view (the msgpack
        response shape).  This is the non-columnar fallback — the hot
        path ships the blocks whole and never calls it."""
        data: Dict[str, Any] = {}
        for name, entry in self.machines.items():
            res: Dict[str, Any] = {}
            for key, (block, index, rows) in entry.items():
                view = self.blocks[block][index]
                if rows is not None:
                    view = view[:rows]
                res[key] = view.item() if view.ndim == 0 else view
            extra = self.rest.get(name)
            if isinstance(extra, dict):
                res.update(extra)
            data[name] = res
        for name, extra in self.rest.items():
            data.setdefault(name, extra)
        return data


def _wire_dtype_name(dt: np.dtype) -> str:
    """The wire spelling of a block dtype (bfloat16 by name — its
    ``dtype.str`` is the ambiguous ``<V2``)."""
    return "bfloat16" if dt.name == "bfloat16" else dt.str


def encode_columnar(obj: Any, wire_dt: Optional[np.dtype] = None) -> bytes:
    """Encode a response object as a GSB1 columnar body.

    ``obj`` is the standard response envelope with a
    :class:`ColumnarResult` under ``"data"`` (or a bare one); any other
    object encodes as a degenerate zero-block body whose rest blob IS
    the msgpack encoding — so the ONE content-negotiation rule holds
    for every route, not just bulk.  Block bytes are shipped straight
    from the arrays' own buffers via ``memoryview`` (the only copy is
    the final ``b"".join``); ``wire_dt`` casts float blocks except the
    scalar-source ones (msgpack parity: python floats are dtype-less).
    """
    col: Optional[ColumnarResult] = None
    if isinstance(obj, ColumnarResult):
        col, rest_obj = obj, {"data": obj.rest}
    elif isinstance(obj, dict) and isinstance(obj.get("data"), ColumnarResult):
        col = obj["data"]
        rest_obj = {k: (col.rest if k == "data" else v) for k, v in obj.items()}
    else:
        rest_obj = obj
    if wire_dt is not None:
        rest_obj = _cast_float_arrays(rest_obj, wire_dt)
    rest_blob = packb(rest_obj)

    specs: List[Dict[str, Any]] = []
    chunks: List[Any] = []
    machines: Dict[str, Any] = {}
    if col is not None:
        for bi, arr in enumerate(col.blocks):
            a = np.ascontiguousarray(arr)
            if a.dtype.byteorder == ">":  # wire format is little-endian
                a = a.astype(a.dtype.newbyteorder("<"))
            if (
                wire_dt is not None
                and bi not in col.scalar_blocks
                and _is_float_leaf(a.dtype)
                and a.dtype != wire_dt
            ):
                a = a.astype(wire_dt)
            specs.append({
                "dtype": _wire_dtype_name(a.dtype),
                "shape": list(a.shape),
                "nbytes": a.nbytes,
            })
            chunks.append(_array_wire_buffer(a) if a.nbytes else b"")
        machines = {
            name: {k: list(v) for k, v in entry.items()}
            for name, entry in col.machines.items()
        }
    header = json.dumps(
        {"rest": len(rest_blob), "blocks": specs, "machines": machines},
        separators=(",", ":"),
    ).encode()
    return b"".join(
        [_COLUMNAR_MAGIC, len(header).to_bytes(4, "little"), header, rest_blob]
        + chunks
    )


def decode_columnar(body: bytes) -> Any:
    """Decode a GSB1 body back to the standard response object.

    Block arrays come back as ZERO-COPY ``np.frombuffer`` views into
    ``body`` (numpy pins the buffer, so the views outlive the caller's
    reference); per-machine dicts are thin index views into those
    blocks.  Value-identical to decoding the msgpack encoding of the
    same response."""
    mv = memoryview(body)
    if bytes(mv[:4]) != _COLUMNAR_MAGIC:
        raise ValueError("not a GSB1 columnar body (bad magic)")
    header_len = int.from_bytes(mv[4:8], "little")
    offset = 8 + header_len
    header = json.loads(bytes(mv[8:offset]))
    rest_len = int(header["rest"])
    obj = unpackb(mv[offset:offset + rest_len])
    offset += rest_len

    blocks: List[np.ndarray] = []
    for spec in header["blocks"]:
        # wire_np_dtype validates → UnsupportedWireDtype → the 415
        dt = wire_np_dtype(str(spec["dtype"]))
        shape = [int(s) for s in spec["shape"]]
        count = 1
        for s in shape:
            count *= s
        blocks.append(
            np.frombuffer(mv, dtype=dt, count=count, offset=offset)
            .reshape(shape)
        )
        offset += int(spec["nbytes"])

    machines = header.get("machines") or {}
    if not machines:
        return obj
    data = obj.setdefault("data", {}) if isinstance(obj, dict) else {}
    col = ColumnarResult(
        blocks=blocks,
        machines={
            name: {k: tuple(v) for k, v in entry.items()}
            for name, entry in machines.items()
        },
        rest=data if isinstance(data, dict) else {},
    )
    merged = col.split()
    if isinstance(obj, dict):
        obj["data"] = merged
        return obj
    return {"data": merged}
