"""Response codecs for the serving hot path.

Reference equivalent: ``flask.jsonify`` over ``ndarray.tolist()`` dicts
(``server/views/base.py``).  Measured on this image, that path encodes
~1.6M floats/s — at TPU scoring rates (~3M sensor-samples/s stacked, each
emitting 2+ floats) the JSON codec becomes the serving ceiling.  Two
replacements, both preserving the response schema:

- :func:`dumps_bytes` — JSON with ndarray leaves encoded by the C
  ``fastjson`` kernel (``gordo_tpu/_native``); non-array values go through
  stdlib json.  Wire-compatible with the old output (same schema; float
  text is shortest-round-trip per dtype rather than repr-of-double).
- :func:`packb` / :func:`unpackb` — msgpack with ndarray leaves as raw
  little-endian buffers (memcpy speed).  Opt-in via the
  ``Accept: application/x-msgpack`` request header; the bundled client
  uses it for bulk scoring.
"""

from __future__ import annotations

import ctypes
import json
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from gordo_tpu._native import load_fastjson

MSGPACK_CONTENT_TYPE = "application/x-msgpack"


class UnsupportedWireDtype(ValueError):
    """A request asked for (or carried) an array dtype the wire format
    does not speak.  The server maps this to HTTP 415 — it is a media
    negotiation failure, not a malformed payload (400) and emphatically
    not a server error (500)."""


def _named_wire_dtypes() -> dict:
    """Canonical wire-dtype names → numpy dtypes.  bfloat16 has no
    unambiguous ``dtype.str`` (numpy renders it ``<V2``), so the wire
    names it explicitly; the rest use their standard numpy spellings."""
    import ml_dtypes

    return {
        "float16": np.dtype(np.float16),
        "float32": np.dtype(np.float32),
        "float64": np.dtype(np.float64),
        "bfloat16": np.dtype(ml_dtypes.bfloat16),
    }


def wire_np_dtype(name: str) -> np.dtype:
    """Resolve a wire dtype string (a negotiate ``dtype=`` parameter or a
    msgpack ``__nd__`` header) to a numpy dtype; raises
    :class:`UnsupportedWireDtype` for anything outside the supported set
    — float16/32/64 + bfloat16 on the float side, standard ints/bools as
    auxiliary payload."""
    named = _named_wire_dtypes()
    if name in named:
        return named[name]
    try:
        dt = np.dtype(name)
    except TypeError:
        raise UnsupportedWireDtype(
            f"unsupported wire dtype {name!r}; supported: "
            f"{', '.join(sorted(named))} and standard integer/bool dtypes"
        )
    if dt.kind in "fiub" and dt.itemsize <= 8:
        return dt
    raise UnsupportedWireDtype(
        f"unsupported wire dtype {name!r}; supported: "
        f"{', '.join(sorted(named))} and standard integer/bool dtypes"
    )


def _accept_wire_dtype(accept: str) -> Optional[np.dtype]:
    """Extract a ``dtype=...`` media-type parameter from an Accept header
    (e.g. ``application/x-msgpack;dtype=bfloat16``): the client's asked-for
    float precision on the wire.  Unknown names raise (→ 415)."""
    for media_range in accept.split(","):
        parts = [p.strip() for p in media_range.split(";")]
        for param in parts[1:]:
            key, _, value = param.partition("=")
            if key.strip().lower() == "dtype":
                return wire_np_dtype(value.strip().strip('"').lower())
    return None


def _cast_float_arrays(obj: Any, dt: np.dtype) -> Any:
    """Recursively cast float ndarray leaves of a response object to the
    negotiated wire dtype (bf16 halves bulk response bytes; values are
    rounded exactly as the dtype dictates — the client opted in)."""
    if isinstance(obj, np.ndarray):
        return obj.astype(dt) if obj.dtype.kind == "f" else obj
    if isinstance(obj, dict):
        return {k: _cast_float_arrays(v, dt) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_cast_float_arrays(v, dt) for v in obj)
    return obj


def negotiate(accept: Optional[str]) -> Tuple[Callable[[Any], bytes], str]:
    """Pick the response encoder for an ``Accept`` header value: msgpack
    when the client asks for it, JSON (native-kernel ndarray leaves)
    otherwise; an optional ``dtype=`` media parameter
    (``application/x-msgpack;dtype=bfloat16``) casts float array leaves
    to that wire precision before encoding — unknown dtype names raise
    :class:`UnsupportedWireDtype` (the server's 415).  The ONE
    content-negotiation rule every response path (server handlers, the
    coalescer's pre-encoded results, benches) must share — divergence
    would make the same request encode differently depending on which
    path served it."""
    accept = accept or ""
    wire_dt = _accept_wire_dtype(accept)
    base: Callable[[Any], bytes]
    if MSGPACK_CONTENT_TYPE in accept:
        base, content_type = packb, MSGPACK_CONTENT_TYPE
    else:
        base, content_type = dumps_bytes, "application/json"
    if wire_dt is None:
        return base, content_type
    return (lambda obj: base(_cast_float_arrays(obj, wire_dt))), content_type

try:
    import msgpack
except ImportError:  # pragma: no cover - msgpack is in the image
    msgpack = None


# ---------------------------------------------------------------------------
# JSON
# ---------------------------------------------------------------------------

def _encode_array_native(a: np.ndarray) -> Optional[bytes]:
    lib = load_fastjson()
    if lib is None or a.ndim not in (1, 2):
        return None
    if a.dtype == np.float32:
        fn, ctype = lib.fj_encode_f32, ctypes.c_float
    elif a.dtype == np.float64:
        fn, ctype = lib.fj_encode_f64, ctypes.c_double
    else:
        return None
    a = np.ascontiguousarray(a)
    rows = a.shape[0]
    cols = a.shape[1] if a.ndim == 2 else 0
    if a.ndim == 2 and cols == 0:
        return None  # zero-width 2-D: let json.dumps produce [[], [], ...]
    cap = a.size * 26 + rows * 2 + 16
    buf = ctypes.create_string_buffer(cap)
    n = fn(a.ctypes.data_as(ctypes.POINTER(ctype)), rows, cols, buf)
    return ctypes.string_at(buf, n)


def _encode_array(a: np.ndarray) -> bytes:
    if (
        a.dtype.kind == "f" and a.dtype.itemsize == 2
    ) or a.dtype.name == "bfloat16":
        # half-precision leaves (f16, bf16): JSON is dtype-less text, and
        # the widening to f32 is exact, so ride the native f32 kernel
        # instead of the slow tolist fallback
        a = a.astype(np.float32)
    out = _encode_array_native(a)
    if out is not None:
        return out
    return json.dumps(a.tolist()).encode()


def _enc(obj: Any, parts: List[bytes]) -> None:
    if isinstance(obj, np.ndarray):
        parts.append(_encode_array(obj))
    elif isinstance(obj, dict):
        parts.append(b"{")
        first = True
        for k, v in obj.items():
            if not first:
                parts.append(b",")
            first = False
            parts.append(json.dumps(str(k)).encode())
            parts.append(b":")
            _enc(v, parts)
        parts.append(b"}")
    elif isinstance(obj, (list, tuple)):
        parts.append(b"[")
        first = True
        for v in obj:
            if not first:
                parts.append(b",")
            first = False
            _enc(v, parts)
        parts.append(b"]")
    elif isinstance(obj, np.generic):  # numpy scalar
        parts.append(json.dumps(obj.item()).encode())
    else:
        parts.append(json.dumps(obj, default=str).encode())


def dumps_bytes(obj: Any) -> bytes:
    """JSON-encode a response object; ndarray leaves ride the C kernel."""
    parts: List[bytes] = []
    _enc(obj, parts)
    return b"".join(parts)


# ---------------------------------------------------------------------------
# msgpack
# ---------------------------------------------------------------------------

def _msgpack_default(o: Any) -> Any:
    if isinstance(o, np.ndarray):
        o = np.ascontiguousarray(o)
        if o.dtype.byteorder == ">":  # wire format is little-endian
            o = o.astype(o.dtype.newbyteorder("<"))
        # bfloat16 has no unambiguous dtype.str ('<V2'); name it on the
        # wire so the decode side doesn't have to guess
        name = (
            "bfloat16" if o.dtype.name == "bfloat16" else o.dtype.str
        )
        return {
            "__nd__": True,
            "dtype": name,
            "shape": list(o.shape),
            "data": o.tobytes(),
        }
    if isinstance(o, np.generic):
        return o.item()
    return str(o)


def _msgpack_hook(d: dict) -> Any:
    if d.get("__nd__"):
        # wire_np_dtype validates: an unknown or disallowed dtype string
        # raises UnsupportedWireDtype → the server's 415, not a 500 from
        # numpy choking on an alien dtype mid-request
        return np.frombuffer(
            d["data"], dtype=wire_np_dtype(str(d["dtype"]))
        ).reshape(d["shape"])
    return d


def packb(obj: Any) -> bytes:
    """msgpack-encode a response; ndarray leaves as raw buffers."""
    if msgpack is None:
        raise RuntimeError("msgpack is not available")
    return msgpack.packb(obj, default=_msgpack_default, use_bin_type=True)


def unpackb(data: bytes) -> Any:
    if msgpack is None:
        raise RuntimeError("msgpack is not available")
    return msgpack.unpackb(data, object_hook=_msgpack_hook, raw=False)
