"""Response codecs for the serving hot path.

Reference equivalent: ``flask.jsonify`` over ``ndarray.tolist()`` dicts
(``server/views/base.py``).  Measured on this image, that path encodes
~1.6M floats/s — at TPU scoring rates (~3M sensor-samples/s stacked, each
emitting 2+ floats) the JSON codec becomes the serving ceiling.  Two
replacements, both preserving the response schema:

- :func:`dumps_bytes` — JSON with ndarray leaves encoded by the C
  ``fastjson`` kernel (``gordo_tpu/_native``); non-array values go through
  stdlib json.  Wire-compatible with the old output (same schema; float
  text is shortest-round-trip per dtype rather than repr-of-double).
- :func:`packb` / :func:`unpackb` — msgpack with ndarray leaves as raw
  little-endian buffers (memcpy speed).  Opt-in via the
  ``Accept: application/x-msgpack`` request header; the bundled client
  uses it for bulk scoring.
"""

from __future__ import annotations

import ctypes
import json
from typing import Any, Callable, List, Optional, Tuple

import numpy as np

from gordo_tpu._native import load_fastjson

MSGPACK_CONTENT_TYPE = "application/x-msgpack"


def negotiate(accept: Optional[str]) -> Tuple[Callable[[Any], bytes], str]:
    """Pick the response encoder for an ``Accept`` header value: msgpack
    when the client asks for it, JSON (native-kernel ndarray leaves)
    otherwise.  The ONE content-negotiation rule every response path
    (server handlers, the coalescer's pre-encoded results, benches) must
    share — divergence would make the same request encode differently
    depending on which path served it."""
    if MSGPACK_CONTENT_TYPE in (accept or ""):
        return packb, MSGPACK_CONTENT_TYPE
    return dumps_bytes, "application/json"

try:
    import msgpack
except ImportError:  # pragma: no cover - msgpack is in the image
    msgpack = None


# ---------------------------------------------------------------------------
# JSON
# ---------------------------------------------------------------------------

def _encode_array_native(a: np.ndarray) -> Optional[bytes]:
    lib = load_fastjson()
    if lib is None or a.ndim not in (1, 2):
        return None
    if a.dtype == np.float32:
        fn, ctype = lib.fj_encode_f32, ctypes.c_float
    elif a.dtype == np.float64:
        fn, ctype = lib.fj_encode_f64, ctypes.c_double
    else:
        return None
    a = np.ascontiguousarray(a)
    rows = a.shape[0]
    cols = a.shape[1] if a.ndim == 2 else 0
    if a.ndim == 2 and cols == 0:
        return None  # zero-width 2-D: let json.dumps produce [[], [], ...]
    cap = a.size * 26 + rows * 2 + 16
    buf = ctypes.create_string_buffer(cap)
    n = fn(a.ctypes.data_as(ctypes.POINTER(ctype)), rows, cols, buf)
    return ctypes.string_at(buf, n)


def _encode_array(a: np.ndarray) -> bytes:
    out = _encode_array_native(a)
    if out is not None:
        return out
    return json.dumps(a.tolist()).encode()


def _enc(obj: Any, parts: List[bytes]) -> None:
    if isinstance(obj, np.ndarray):
        parts.append(_encode_array(obj))
    elif isinstance(obj, dict):
        parts.append(b"{")
        first = True
        for k, v in obj.items():
            if not first:
                parts.append(b",")
            first = False
            parts.append(json.dumps(str(k)).encode())
            parts.append(b":")
            _enc(v, parts)
        parts.append(b"}")
    elif isinstance(obj, (list, tuple)):
        parts.append(b"[")
        first = True
        for v in obj:
            if not first:
                parts.append(b",")
            first = False
            _enc(v, parts)
        parts.append(b"]")
    elif isinstance(obj, np.generic):  # numpy scalar
        parts.append(json.dumps(obj.item()).encode())
    else:
        parts.append(json.dumps(obj, default=str).encode())


def dumps_bytes(obj: Any) -> bytes:
    """JSON-encode a response object; ndarray leaves ride the C kernel."""
    parts: List[bytes] = []
    _enc(obj, parts)
    return b"".join(parts)


# ---------------------------------------------------------------------------
# msgpack
# ---------------------------------------------------------------------------

def _msgpack_default(o: Any) -> Any:
    if isinstance(o, np.ndarray):
        o = np.ascontiguousarray(o)
        if o.dtype.byteorder == ">":  # wire format is little-endian
            o = o.astype(o.dtype.newbyteorder("<"))
        return {
            "__nd__": True,
            "dtype": o.dtype.str,
            "shape": list(o.shape),
            "data": o.tobytes(),
        }
    if isinstance(o, np.generic):
        return o.item()
    return str(o)


def _msgpack_hook(d: dict) -> Any:
    if d.get("__nd__"):
        return np.frombuffer(
            d["data"], dtype=np.dtype(d["dtype"])
        ).reshape(d["shape"])
    return d


def packb(obj: Any) -> bytes:
    """msgpack-encode a response; ndarray leaves as raw buffers."""
    if msgpack is None:
        raise RuntimeError("msgpack is not available")
    return msgpack.packb(obj, default=_msgpack_default, use_bin_type=True)


def unpackb(data: bytes) -> Any:
    if msgpack is None:
        raise RuntimeError("msgpack is not available")
    return msgpack.unpackb(data, object_hook=_msgpack_hook, raw=False)
