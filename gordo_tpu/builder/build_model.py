"""Training driver.

Reference equivalent: ``gordo_components/builder/build_model.py`` —
``build_model`` (dataset → model construction → CV → final fit → metadata)
and ``provide_saved_model`` (config-hash cache over a disk registry +
``serializer.dump``).

Call stack parity with SURVEY.md §4.1; the hot loop inside is the jitted
XLA fit instead of per-pod Keras.  Fleet-scale builds (thousands of
machines as one sharded XLA program) layer on top in
``gordo_tpu.parallel.fleet`` — this module is the single-machine path and
the metadata/cache contract both share.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

import gordo_tpu
from gordo_tpu import artifacts, serializer
from gordo_tpu.dataset.base import GordoBaseDataset
from gordo_tpu.utils import disk_registry, profiling

logger = logging.getLogger(__name__)


def calculate_model_key(
    name: str,
    model_config: dict,
    data_config: dict,
    metadata: Optional[dict] = None,
    extra: Optional[dict] = None,
) -> str:
    """Deterministic cache key: md5 over (version, name, configs, metadata)
    (reference: ``_calculate_model_key``).  Any config or framework-version
    change produces a new key → rebuild.  ``extra`` carries build-time
    options that change the trained result without living in the configs
    (e.g. ``align_lengths``); omitted/empty keeps the historical hash so
    existing caches stay valid."""
    payload_dict = {
        "gordo_tpu_version": gordo_tpu.__version__,
        "name": name,
        "model_config": model_config,
        "data_config": data_config,
        "user_metadata": metadata or {},
    }
    if extra:
        payload_dict["build_options"] = extra
    payload = json.dumps(payload_dict, sort_keys=True, default=str)
    return hashlib.md5(payload.encode()).hexdigest()


def build_model(
    name: str,
    model_config: dict,
    data_config: dict,
    metadata: Optional[dict] = None,
    evaluation_config: Optional[dict] = None,
) -> Tuple[Any, Dict[str, Any]]:
    """Build one machine's model: data → model → (CV) → fit → metadata."""
    evaluation_config = evaluation_config or {"cv_mode": "full_build"}
    t_start = time.time()
    from gordo_tpu.utils.compile_cache import enable_persistent_compile_cache

    enable_persistent_compile_cache()

    dataset = GordoBaseDataset.from_dict(dict(data_config))
    # X and y may alias the SAME DataFrame (autoencoder default where
    # targets == inputs) — treat both as read-only; np.asarray below copies
    X, y = dataset.get_data()
    t_data = time.time()

    model = serializer.from_definition(dict(model_config))

    X_arr = np.asarray(X, dtype=np.float32)
    y_arr = np.asarray(y, dtype=np.float32)

    cv_meta: Dict[str, Any] = {}
    cv_duration = 0.0
    cv_mode = evaluation_config.get("cv_mode", "full_build")
    if cv_mode != "build_only" and hasattr(model, "cross_validate"):
        t0 = time.time()
        with profiling.trace(f"cv/{name}"):
            model.cross_validate(X_arr, y_arr, cv=evaluation_config.get("cv"))
        cv_duration = time.time() - t0
        cv_meta = getattr(model, "cv_metadata_", {})

    if cv_mode == "cross_val_only":
        fit_duration = 0.0
    else:
        t0 = time.time()
        with profiling.trace(f"fit/{name}"):
            model.fit(X_arr, y_arr)
        fit_duration = time.time() - t0

    build_metadata = assemble_metadata(
        name=name,
        model=model,
        model_config=model_config,
        data_config=data_config,
        dataset_metadata=dataset.get_metadata(),
        metadata=metadata,
        data_query_duration=t_data - t_start,
        cv_duration=cv_duration,
        fit_duration=fit_duration,
        cv_meta=cv_meta,
    )
    if cv_mode != "cross_val_only":
        # training-time residual sketch: the fleet-health drift baseline
        # (scored through the serving path; GORDO_FLEET_BASELINE=off
        # skips, and non-anomaly models simply record none)
        from gordo_tpu.telemetry.fleet_health import training_baseline

        baseline = training_baseline(model, X_arr)
        if baseline is not None:
            build_metadata["fleet-health"] = {
                "version": 1, "baseline": baseline,
            }
    return model, build_metadata


def assemble_metadata(
    name: str,
    model: Any,
    model_config: dict,
    data_config: dict,
    dataset_metadata: dict,
    metadata: Optional[dict],
    data_query_duration: float,
    cv_duration: float,
    fit_duration: float,
    cv_meta: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """The machine-metadata schema shared by the single-machine and fleet
    builders (reference parity: the metadata JSON is the primary
    observability artifact, SURVEY.md §6.5)."""
    metadata = metadata or {}
    cv_meta = cv_meta or {}
    return {
        "name": name,
        "gordo_tpu_version": gordo_tpu.__version__,
        "checksum": calculate_model_key(name, model_config, data_config, metadata),
        "dataset": dataset_metadata,
        "model": {
            "model_config": model_config,
            "model_creation_date": time.strftime("%Y-%m-%d %H:%M:%S%z"),
            "data_query_duration_sec": data_query_duration,
            "cross_validation_duration_sec": cv_duration,
            "model_builder_duration_sec": fit_duration,
            **(
                {
                    "fit_samples_per_second": round(
                        dataset_metadata["rows_after_filter"] / fit_duration, 1
                    )
                }
                if fit_duration and dataset_metadata.get("rows_after_filter")
                else {}
            ),
            **(
                {"cross_validation": cv_meta}
                if cv_meta
                else {}
            ),
            **(
                model.get_metadata() if hasattr(model, "get_metadata") else {}
            ),
        },
        "user_defined": metadata,
    }


def lookup_cached_artifact(
    model_register_dir: str, cache_key: str, name: str
) -> Optional[str]:
    """Registry lookup that verifies the artifact still IS what the key
    says: per-machine artifact dirs get overwritten on config-changed
    rebuilds, so a stale registry entry can point at a dir now holding a
    DIFFERENT build.  Artifacts stamp their own ``cache_key`` in metadata
    at dump time; a mismatch is treated as a miss.  (Artifacts from before
    this stamp carry no key and are accepted as-is.)"""
    cached = disk_registry.get_value(model_register_dir, cache_key)
    if not cached:
        return None
    if artifacts.is_pack_ref(cached):
        # v2: the registry records a pack ref; resolve it through the
        # pack index (machine present + stamped cache key matches +
        # pack validates) — the same verify-the-pointer contract as the
        # v1 dir checks below
        resolved = artifacts.resolve_cached(cached, cache_key)
        if resolved is None:
            logger.warning(
                "Registry entry for %s points at a stale/invalid pack "
                "ref %s; rebuilding", name, cached,
            )
            return None
        logger.info("Cache hit for %s (key %s): %s", name, cache_key, cached)
        return resolved
    if not os.path.exists(os.path.join(cached, serializer.MODEL_FILE)):
        logger.warning(
            "Registry entry for %s points at missing artifact %s; rebuilding",
            name, cached,
        )
        return None
    try:
        stored = serializer.load_metadata(cached).get("cache_key")
    except Exception:
        stored = None
    if stored is not None and stored != cache_key:
        logger.warning(
            "Artifact %s was overwritten by a different build (stored key "
            "%s != %s); treating as cache miss", cached, stored, cache_key,
        )
        return None
    logger.info("Cache hit for %s (key %s): %s", name, cache_key, cached)
    return cached


def provide_saved_model(
    name: str,
    model_config: dict,
    data_config: dict,
    metadata: Optional[dict] = None,
    output_dir: str = "./models",
    model_register_dir: Optional[str] = None,
    replace_cache: bool = False,
    evaluation_config: Optional[dict] = None,
) -> str:
    """Cache-aware build: return an artifact dir, training only on miss
    (reference: ``provide_saved_model``)."""
    cache_key = calculate_model_key(name, model_config, data_config, metadata)

    if model_register_dir and not replace_cache:
        cached = lookup_cached_artifact(model_register_dir, cache_key, name)
        if cached is not None:
            return cached

    model, build_metadata = build_model(
        name, model_config, data_config, metadata, evaluation_config
    )
    build_metadata["cache_key"] = cache_key
    dest = os.path.join(output_dir, name) if os.path.basename(
        os.path.normpath(output_dir)
    ) != name else output_dir
    serializer.dump(model, dest, metadata=build_metadata)

    if model_register_dir:
        disk_registry.write_key(model_register_dir, cache_key, os.path.abspath(dest))
    return dest
