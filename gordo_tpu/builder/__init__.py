from gordo_tpu.builder.build_model import (  # noqa: F401
    assemble_metadata,
    build_model,
    calculate_model_key,
    provide_saved_model,
)
from gordo_tpu.builder.fleet_build import (  # noqa: F401
    ProjectBuildResult,
    build_project,
)
