from gordo_tpu.builder.build_model import (  # noqa: F401
    build_model,
    calculate_model_key,
    provide_saved_model,
)
