"""Project-scale builds: the whole machine list through the fleet engine.

Reference equivalent: the Argo workflow's fan-out — N independent
``gordo build`` pods, one per machine, each running
``builder/build_model.py::provide_saved_model`` (SURVEY.md §4.4).

TPU-native replacement: machines are bucketed by model-signature +
data-shape; each bucket trains as ONE stacked XLA program
(``gordo_tpu.parallel.anomaly.FleetDiffBuilder``) sharded over the device
mesh.  Per-machine contracts are preserved exactly: every machine still
gets its own artifact directory, metadata JSON, and config-hash cache entry
(``provide_saved_model`` cache parity) — a re-run project build skips
already-built machines, and a machine whose config the fleet engine can't
express falls back to the single-machine builder transparently.

Data loading stays host-side and overlaps across machines via a thread
pool (the reference's per-pod I/O becomes concurrent per-tag reads feeding
one process).
"""

from __future__ import annotations

import logging
import os
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
from jax.sharding import Mesh

from gordo_tpu import serializer
from gordo_tpu.builder.build_model import (
    assemble_metadata,
    build_model,
    calculate_model_key,
)
from gordo_tpu.dataset.base import GordoBaseDataset
from gordo_tpu.parallel.anomaly import FleetDiffBuilder, analyze_definition
from gordo_tpu.utils import disk_registry, profiling
from gordo_tpu.workflow.config import Machine

logger = logging.getLogger(__name__)

#: fleet programs are chunked so a bucket's stacked arrays stay well inside
#: device memory (tiny models: the data, not the params, is the footprint).
DEFAULT_MAX_BUCKET = 512


class ProjectBuildResult:
    """Per-machine artifact dirs + build accounting for one project build."""

    def __init__(self):
        self.artifacts: Dict[str, str] = {}
        self.cached: List[str] = []
        self.fleet_built: List[str] = []
        self.single_built: List[str] = []
        self.failed: Dict[str, str] = {}
        self.seconds: float = 0.0

    def summary(self) -> Dict[str, Any]:
        return {
            "n_machines": len(self.artifacts) + len(self.failed),
            "cached": len(self.cached),
            "fleet_built": len(self.fleet_built),
            "single_built": len(self.single_built),
            "failed": dict(self.failed),
            "build_seconds": self.seconds,
        }


def _as_machine(m: Union[Machine, Dict[str, Any]]) -> Machine:
    if isinstance(m, Machine):
        return m
    return Machine.from_config(m)


def build_project(
    machines: Sequence[Union[Machine, Dict[str, Any]]],
    output_dir: str,
    model_register_dir: Optional[str] = None,
    mesh: Optional[Mesh] = None,
    replace_cache: bool = False,
    max_bucket_size: int = DEFAULT_MAX_BUCKET,
    data_workers: int = 8,
) -> ProjectBuildResult:
    """Build every machine; fleet-bucket the homogeneous ones.

    Returns a :class:`ProjectBuildResult` with one artifact dir per machine
    (identical layout to ``provide_saved_model``).
    """
    t_start = time.time()
    machines = [_as_machine(m) for m in machines]
    result = ProjectBuildResult()

    # 1. Config-hash cache check (reference: provide_saved_model).
    to_build: List[Machine] = []
    for m in machines:
        key = calculate_model_key(m.name, m.model, m.dataset, m.metadata)
        if model_register_dir and not replace_cache:
            cached = disk_registry.get_value(model_register_dir, key)
            if cached and os.path.exists(
                os.path.join(cached, serializer.MODEL_FILE)
            ):
                logger.info("Cache hit for %s: %s", m.name, cached)
                result.artifacts[m.name] = cached
                result.cached.append(m.name)
                continue
        to_build.append(m)

    # 2. Load data concurrently (host-side, I/O-bound).
    def _load(m: Machine):
        t0 = time.time()
        dataset = GordoBaseDataset.from_dict(dict(m.dataset))
        X, y = dataset.get_data()
        return (
            np.asarray(X, np.float32),
            np.asarray(y, np.float32),
            dataset.get_metadata(),
            time.time() - t0,
        )

    loaded: Dict[str, Tuple] = {}
    if to_build:
        with ThreadPoolExecutor(max_workers=data_workers) as pool:
            futures = {m.name: pool.submit(_load, m) for m in to_build}
        for m in to_build:
            try:
                loaded[m.name] = futures[m.name].result()
            except Exception as exc:  # data failures shouldn't sink the fleet
                logger.exception("Data load failed for %s", m.name)
                result.failed[m.name] = f"data: {exc}"
    to_build = [m for m in to_build if m.name in loaded]

    # 3. Bucket by (fleet signature, feature shapes); misfits go single.
    buckets: Dict[Tuple, List[Machine]] = {}
    singles: List[Machine] = []
    specs: Dict[Tuple, Any] = {}
    for m in to_build:
        X, y, _, _ = loaded[m.name]
        cv_mode = m.evaluation.get("cv_mode", "full_build")
        spec = None
        if cv_mode == "full_build":
            try:
                spec = analyze_definition(serializer.from_definition(dict(m.model)))
            except Exception:
                spec = None
        if spec is None:
            singles.append(m)
            continue
        key = (spec.signature, X.shape[1], y.shape[1], str(m.evaluation.get("cv")))
        buckets.setdefault(key, []).append(m)
        specs[key] = spec

    # 4. Fleet-build each bucket in chunks.
    for key, bucket in buckets.items():
        spec = specs[key]
        cv = bucket[0].evaluation.get("cv")
        for start in range(0, len(bucket), max_bucket_size):
            chunk = bucket[start : start + max_bucket_size]
            t0 = time.time()
            try:
                builder = FleetDiffBuilder(spec, cv=cv, mesh=mesh)
                with profiling.trace(f"fleet_bucket/{len(chunk)}"):
                    detectors = builder.build(
                        [loaded[m.name][0] for m in chunk],
                        [loaded[m.name][1] for m in chunk],
                    )
            except Exception as exc:
                logger.exception("Fleet bucket failed; falling back to singles")
                singles.extend(chunk)
                continue
            fleet_seconds = time.time() - t0
            for m, det in zip(chunk, detectors):
                _dump_machine(
                    m,
                    det,
                    loaded[m.name],
                    fleet_seconds / len(chunk),
                    output_dir,
                    model_register_dir,
                    result,
                    fleet=True,
                )

    # 5. Single-machine fallback (non-fleetable configs).
    for m in singles:
        try:
            model, metadata = build_model(
                m.name, m.model, m.dataset, m.metadata, m.evaluation
            )
        except Exception as exc:
            logger.exception("Single build failed for %s", m.name)
            result.failed[m.name] = f"build: {exc}"
            continue
        dest = os.path.join(output_dir, m.name)
        serializer.dump(model, dest, metadata=metadata)
        _register(m, dest, model_register_dir)
        result.artifacts[m.name] = dest
        result.single_built.append(m.name)

    result.seconds = time.time() - t_start
    return result


def _dump_machine(
    m: Machine,
    detector,
    loaded_entry: Tuple,
    fit_seconds: float,
    output_dir: str,
    model_register_dir: Optional[str],
    result: ProjectBuildResult,
    fleet: bool,
) -> None:
    _, _, dataset_meta, query_seconds = loaded_entry
    metadata = assemble_metadata(
        name=m.name,
        model=detector,
        model_config=m.model,
        data_config=m.dataset,
        dataset_metadata=dataset_meta,
        metadata=m.metadata,
        data_query_duration=query_seconds,
        cv_duration=fit_seconds,  # fleet: CV+fit are one fused program
        fit_duration=fit_seconds,
        cv_meta=getattr(detector, "cv_metadata_", {}),
    )
    metadata["model"]["fleet_built"] = fleet
    dest = os.path.join(output_dir, m.name)
    serializer.dump(detector, dest, metadata=metadata)
    _register(m, dest, model_register_dir)
    result.artifacts[m.name] = dest
    result.fleet_built.append(m.name)


def _register(m: Machine, dest: str, model_register_dir: Optional[str]) -> None:
    if model_register_dir:
        key = calculate_model_key(m.name, m.model, m.dataset, m.metadata)
        disk_registry.write_key(model_register_dir, key, os.path.abspath(dest))
