"""Project-scale builds: the whole machine list through the fleet engine.

Reference equivalent: the Argo workflow's fan-out — N independent
``gordo build`` pods, one per machine, each running
``builder/build_model.py::provide_saved_model`` (SURVEY.md §4.4).

TPU-native replacement: machines are bucketed by model-signature +
data-shape; each bucket trains as ONE stacked XLA program
(``gordo_tpu.parallel.anomaly.FleetDiffBuilder``) sharded over the device
mesh.  Per-machine contracts are preserved exactly: every machine still
gets its own artifact directory, metadata JSON, and config-hash cache entry
(``provide_saved_model`` cache parity) — a re-run project build skips
already-built machines, and a machine whose config the fleet engine can't
express falls back to the single-machine builder transparently.

Data loading stays host-side, streaming, and memory-bounded: machines are
bucketed by CONFIG alone (model signature + tag widths — no data needed),
then built chunk by chunk with the loader pool prefetching exactly ONE
chunk ahead while the device trains the current one.  Peak host memory is
two chunks of arrays (2 x ``max_bucket_size`` machines), not the whole
project — the reference held one machine per pod; a 10k-machine
load-everything pass here would be tens of GB.  Arrays free as soon as a
machine's artifact is dumped; ``ProjectBuildResult.peak_loaded`` records
the high-water mark so tests can hold the bound.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import math
import os
import shutil
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from gordo_tpu import artifacts, serializer, telemetry
from gordo_tpu.mesh import Mesh
from gordo_tpu.builder.build_model import (
    assemble_metadata,
    build_model,
    calculate_model_key,
    lookup_cached_artifact,
)
from gordo_tpu.dataset.base import GordoBaseDataset
from gordo_tpu.ingest import plane as ingest_plane
from gordo_tpu.parallel.anomaly import (
    FleetDiffBuilder,
    _model_axis_pad,
    analyze_definition,
)
from gordo_tpu.utils import disk_registry, profiling
from gordo_tpu.workflow.config import Machine

logger = logging.getLogger(__name__)

# -- telemetry instruments (docs/observability.md) --------------------------
_BUILD_MACHINES_TOTAL = telemetry.counter(
    "gordo_build_machines_total",
    "Machines resolved by project builds, by path taken",
    labels=("path",),  # cached | fleet | single | failed
)
_BUILD_MACHINE_SECONDS = telemetry.histogram(
    "gordo_build_machine_seconds",
    "Per-machine build seconds (fleet machines: bucket seconds / size)",
    labels=("path",),
)
_BUILD_BUCKET_SECONDS = telemetry.histogram(
    "gordo_build_bucket_seconds",
    "Stacked CV+fit seconds per fleet chunk",
)
_DATA_LOAD_SECONDS = telemetry.histogram(
    "gordo_build_data_load_seconds",
    "Per-machine dataset load+assembly seconds (loader pool)",
)

# -- build-pipeline instruments (docs/perf.md "Build pipeline") -------------
_PIPE_STAGE_SECONDS = telemetry.histogram(
    "gordo_build_pipeline_stage_seconds",
    "Busy seconds per pipeline stage unit "
    "(load: one machine, device: one chunk, write: one artifact)",
    labels=("stage",),
)
_PIPE_STALL_SECONDS = telemetry.counter(
    "gordo_build_pipeline_stall_seconds",
    "Seconds the pipeline drive loop stalled on a stage "
    "(load: waiting for the loader pool, write: writer queue full)",
    labels=("stage",),
)
_PIPE_WRITER_QUEUE_DEPTH = telemetry.gauge(
    "gordo_build_pipeline_writer_queue_depth",
    "Artifact writes queued or in flight in the background writer pool",
)
_PIPE_CHUNKS_TOTAL = telemetry.counter(
    "gordo_build_pipeline_chunks_total",
    "Fleet chunks driven to completion, by execution path",
    labels=("path",),  # pipelined | serial
)
_PIPE_DEVICE_IDLE_SECONDS = telemetry.counter(
    "gordo_build_device_idle_seconds",
    "Seconds the drive loop held NO dispatched fleet program in flight "
    "(host-side lower bound on device idle: load/fetch/assemble/write "
    "time the pipeline failed to hide behind device compute)",
)
_PIPE_DEVICE_INFLIGHT = telemetry.gauge(
    "gordo_build_device_inflight",
    "Fleet chunk programs dispatched but not yet collected",
)


# -- incremental refresh knobs (docs/configuration.md) ----------------------
#: fraction of the configured epochs a warm-start rebuild trains for —
#: the previous generation's weights are most of the way there already
ENV_REFRESH_EPOCH_FRACTION = "GORDO_REFRESH_EPOCH_FRACTION"
DEFAULT_REFRESH_EPOCH_FRACTION = 0.25
#: parity gate: the warm rebuild's final training loss must stay within
#: this factor of the previous artifact's recorded final loss, or the
#: machine rebuilds cold (full epochs, fresh init) with the reason attested
#: in its metadata
ENV_REFRESH_PARITY_FACTOR = "GORDO_REFRESH_PARITY_FACTOR"
DEFAULT_REFRESH_PARITY_FACTOR = 1.5


def _refresh_epoch_fraction() -> float:
    try:
        frac = float(os.environ.get(
            ENV_REFRESH_EPOCH_FRACTION, DEFAULT_REFRESH_EPOCH_FRACTION
        ))
    except ValueError:
        return DEFAULT_REFRESH_EPOCH_FRACTION
    return min(max(frac, 0.0), 1.0)


def _refresh_parity_factor() -> float:
    try:
        return float(os.environ.get(
            ENV_REFRESH_PARITY_FACTOR, DEFAULT_REFRESH_PARITY_FACTOR
        ))
    except ValueError:
        return DEFAULT_REFRESH_PARITY_FACTOR


def _warm_epochs(cfg) -> int:
    """Reduced-epoch budget for a warm-start fit (never below 1)."""
    return max(1, math.ceil(cfg.epochs * _refresh_epoch_fraction()))


def _detector_estimator(detector):
    """The trained JAX estimator inside a detector/pipeline artifact."""
    from gordo_tpu.pipeline import Pipeline

    base = getattr(detector, "base_estimator", detector)
    return base._final if isinstance(base, Pipeline) else base


def _resolve_warm_params(
    output_dir: str, names: Sequence[str]
) -> Dict[str, Tuple[Any, Optional[float]]]:
    """Previous-generation warm-start material via zero-copy
    :class:`~gordo_tpu.artifacts.PackStore` reads:
    ``{name: (params pytree, previous final training loss)}``.

    Machines the pack index doesn't know (first build, v1-only artifact)
    are simply absent — the caller rebuilds them cold and attests why.
    The arrays stay memory-mapped until the fleet program stacks them, so
    resolving a subset never reads the rest of the fleet's bytes."""
    try:
        store = artifacts.open_store(output_dir)
    except Exception:
        logger.exception(
            "warm-start: pack store open failed under %s", output_dir
        )
        return {}
    if store is None:
        return {}
    resolved: Dict[str, Tuple[Any, Optional[float]]] = {}
    for name in names:
        if name not in store:
            continue
        try:
            est = _detector_estimator(store.load_model(name))
            params = getattr(est, "params_", None)
            if params is None:
                continue
            hist = getattr(est, "history_", None)
            prev_loss = (
                float(np.asarray(hist).ravel()[-1])
                if hist is not None and np.size(hist) else None
            )
        except Exception:
            logger.exception(
                "warm-start: could not resolve previous params for %s", name
            )
            continue
        resolved[name] = (params, prev_loss)
    return resolved


def _pipeline_enabled(pipeline: Optional[bool]) -> bool:
    """Kill switch: ``GORDO_BUILD_PIPELINE=off`` (or ``0``/``false``)
    forces the serial drive loop; an explicit ``pipeline=`` argument to
    :func:`build_project` wins over the environment."""
    if pipeline is not None:
        return bool(pipeline)
    return os.environ.get("GORDO_BUILD_PIPELINE", "on").strip().lower() not in (
        "off", "0", "false",
    )


class _ArtifactWriter:
    """Background artifact-writer pool — stage C of the build pipeline.

    ``serializer.dump`` (pickle + YAML + JSON per machine) runs off the
    device critical path on a small thread pool behind a BOUNDED queue:
    :meth:`submit` blocks once ``max_queued`` writes are outstanding, so
    a slow disk backpressures the drive loop instead of buffering
    unbounded pickled fleets.  The write function is expected to place
    each artifact atomically (scratch dir + rename — see
    :func:`_write_artifact`) and to do its own failure recording;
    ``drain()`` blocks until every queued write finished.  The resumable
    exit-75 path drains BEFORE the shard state transitions, so recorded
    progress never references a half-written artifact.
    """

    def __init__(
        self,
        write_fn: Callable[..., None],
        max_workers: int = 1,
        max_queued: int = 512,
    ):
        # one worker by default: artifact pickling is GIL-bound, so extra
        # writer threads buy no parallelism and cost switch churn on
        # small hosts (the bench container is 1-core)
        self._write_fn = write_fn
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="gordo-artifact-writer"
        )
        self._slots = threading.BoundedSemaphore(max_queued)
        self._lock = threading.Lock()
        self._depth = 0
        self._futures: List[Any] = []

    def submit(self, items: Sequence[Tuple]) -> None:
        """Queue one chunk's artifact writes as a single pool task (one
        handoff per chunk, not per machine).  Blocks for queue slots —
        one per artifact — when the writer is ``max_queued`` behind."""
        t0 = time.time()
        for _ in items:
            self._slots.acquire()
        stall = time.time() - t0
        if stall > 0.001:
            _PIPE_STALL_SECONDS.inc(stall, "write")
        with self._lock:
            self._depth += len(items)
            _PIPE_WRITER_QUEUE_DEPTH.set(float(self._depth))
        self._futures.append(self._pool.submit(self._run, list(items)))

    def _run(self, items: List[Tuple]) -> None:
        for args in items:
            t0 = time.time()
            try:
                self._write_fn(*args)
            finally:
                self._slots.release()
                with self._lock:
                    self._depth -= 1
                    _PIPE_WRITER_QUEUE_DEPTH.set(float(self._depth))
                _PIPE_STAGE_SECONDS.observe(time.time() - t0, "write")

    def drain(self) -> None:
        """Block until every queued write has completed, then shut the
        pool down.  Write errors are recorded by the write function, not
        raised here — a failed dump must fail ONE machine, not the drain."""
        futures, self._futures = self._futures, []
        for fut in futures:
            fut.result()
        self._pool.shutdown(wait=True)


#: fleet programs are chunked so a bucket's stacked arrays stay well inside
#: device memory (tiny models: the data, not the params, is the footprint).
#: Hardware sweep (v5e via tunnel, r4, 512 ff machines): warm build rate is
#: 131k models/h at 128, 188k at 256, 184k at 512 — flat at >=256, so 512
#: stands (fewer chunks per big project at the same rate).
DEFAULT_MAX_BUCKET = 512

#: recurrent (lookback-windowed) signatures chunk smaller: the r6
#: machines-per-bucket sweep (`scripts/sweep_constants.py lstmbucket`,
#: CPU jax, docs/perf.md) measured the warm CV+fit rate DECLINING with
#: bucket size (5,019 models/h at 64 → 3,895 at 512 — wider vmap, more
#: cache pressure) while the cold rate peaks mid-table (compile
#: amortization).  128 sits within 10% of the best warm rate, builds
#: cold 18% faster than 64, and keeps 4x headroom vs 512 on the windows
#: tensors (∝ machines × rows × lookback × tags) that bound LSTM
#: dispatches.  Re-sweep on TPU when the tunnel allows: tunnel dispatch
#: overhead (~230ms/chunk) favors bigger buckets than CPU does.
DEFAULT_MAX_BUCKET_LSTM = 128


#: auto-pad (VERDICT weak #4): when neither ragged strategy is chosen and
#: the config-level estimate predicts more than this many seconds of
#: per-distinct-length XLA compiles, ``build_project`` turns on
#: ``pad_lengths`` itself rather than only warning.  300s ≈ 22 distinct
#: lengths at the measured ~13.7s/compile — small ragged dev projects
#: (a handful of lengths) stay in exact-parity mode, while the
#: 1000-machine filtered project that forgot the flag no longer pays the
#: hour of compiles the feature was built to kill.
DEFAULT_AUTO_PAD_BUDGET_SECONDS = 300.0

#: the alignment auto-pad selects.  128 collapses any ragged bucket to
#: ~(length range)/128 programs at a bounded cost of < 128 weight-masked
#: rows per machine, and is large enough that the row counts row
#: filtering produces in practice (thousands) land in few groups.  An
#: explicit ``pad_lengths`` always wins over this default.
DEFAULT_AUTO_PAD_LENGTHS = 128


def estimate_ragged_compile_seconds(machines: Sequence[Machine]) -> float:
    """Config-level estimate of the EXTRA XLA compile seconds an exact-mode
    build of ``machines`` would pay for ragged train lengths (one program
    per distinct row count beyond the one-per-bucket floor).  The same
    estimator ``workflow plan`` prints its warning from."""
    # lazy import: workflow.generator imports gordo_tpu.builder at module
    # scope, so a top-level import here would cycle
    from gordo_tpu.workflow.generator import (
        COMPILE_SECONDS_PER_LENGTH,
        _fleet_signature,
        _ragged_length_estimate,
    )

    buckets: Dict[str, List[Machine]] = {}
    for m in machines:
        buckets.setdefault(_fleet_signature(m), []).append(m)
    if not buckets:
        return 0.0
    est_lengths = sum(
        _ragged_length_estimate(members) for members in buckets.values()
    )
    extra = est_lengths - len(buckets)  # 1 compile per bucket is the floor
    return max(0.0, extra * COMPILE_SECONDS_PER_LENGTH)


def default_bucket_size(spec) -> int:
    """Per-signature ``max_bucket_size`` default: recurrent estimators
    (``lookback_window > 1`` — LSTM family) chunk at
    ``DEFAULT_MAX_BUCKET_LSTM``, everything else at
    ``DEFAULT_MAX_BUCKET``."""
    est = getattr(spec, "estimator_proto", None)
    if getattr(est, "lookback_window", 1) > 1:
        return DEFAULT_MAX_BUCKET_LSTM
    return DEFAULT_MAX_BUCKET


class ProjectBuildResult:
    """Per-machine artifact dirs + build accounting for one project build."""

    def __init__(self):
        self.artifacts: Dict[str, str] = {}
        self.cached: List[str] = []
        self.fleet_built: List[str] = []
        self.single_built: List[str] = []
        self.failed: Dict[str, str] = {}
        self.seconds: float = 0.0
        #: high-water mark of machines whose (X, y) arrays were resident at
        #: once — the streaming pipeline bounds this at two chunks
        self.peak_loaded: int = 0
        #: the pad_lengths value auto-selected by the ragged-strategy
        #: heuristic (None when off, explicit, or not triggered)
        self.auto_pad: Optional[int] = None
        #: (process_id, num_processes) when this was one shard of a
        #: multi-host build
        self.shard: Optional[Tuple[int, int]] = None
        #: whether the pipelined drive loop ran (False: serial path via
        #: the GORDO_BUILD_PIPELINE=off kill switch or pipeline=False)
        self.pipelined: bool = False
        #: seconds the drive loop held no dispatched fleet program in
        #: flight (see ``_DeviceOccupancy``) — the pipeline's
        #: dispatch-overlap headroom, measurable even on CPU
        self.device_idle_seconds: float = 0.0
        #: artifact format this build wrote ("v1" per-machine dirs, "v2"
        #: memory-mapped bucket packs — see gordo_tpu/artifacts/)
        self.artifact_format: str = "v1"
        #: machines rebuilt from the previous generation's params under
        #: the parity gate (warm_start=True builds only)
        self.warm_started: List[str] = []
        #: machines a warm_start build rebuilt COLD, with the attested
        #: reason (no previous params / parity gate / single path / ...)
        self.warm_fallbacks: Dict[str, str] = {}
        #: the published artifact generation after this build's stamp
        #: (v2 only; None for v1 builds)
        self.generation: Optional[int] = None
        #: resolved loader-pool thread count (adaptive when the caller
        #: passed data_workers=None — see build_project)
        self.loader_workers: int = 0
        #: build-ingest plane accounting (None when GORDO_INGEST is off):
        #: machines / fetches / dedup_hits / vectorized / fallback counts
        #: accumulated across chunks by ingest.plane.load_chunk
        self.ingest: Optional[Dict[str, Any]] = None

    def summary(self) -> Dict[str, Any]:
        out = {
            "n_machines": len(self.artifacts) + len(self.failed),
            "cached": len(self.cached),
            "fleet_built": len(self.fleet_built),
            "single_built": len(self.single_built),
            "failed": dict(self.failed),
            "build_seconds": self.seconds,
            "peak_loaded_machines": self.peak_loaded,
            "pipelined": self.pipelined,
            "device_idle_seconds": self.device_idle_seconds,
            "artifact_format": self.artifact_format,
        }
        if self.loader_workers:
            out["loader_workers"] = self.loader_workers
        if self.ingest is not None:
            out["ingest"] = dict(self.ingest)
        if self.warm_started or self.warm_fallbacks:
            out["warm_started"] = len(self.warm_started)
            out["warm_fallbacks"] = dict(self.warm_fallbacks)
        if self.generation is not None:
            out["generation"] = self.generation
        if self.auto_pad:
            out["auto_pad_lengths"] = self.auto_pad
        if self.shard:
            out["shard"] = {
                "process_id": self.shard[0],
                "num_processes": self.shard[1],
                "machines": sorted(self.artifacts) + sorted(self.failed),
            }
        return out


class _LoadTracker:
    """Counts machines with live arrays; records the high-water mark."""

    def __init__(self):
        self._lock = threading.Lock()
        self.current = 0
        self.peak = 0

    def acquire(self) -> None:
        with self._lock:
            self.current += 1
            self.peak = max(self.peak, self.current)

    def release(self, n: int = 1) -> None:
        with self._lock:
            self.current -= n


class _DeviceOccupancy:
    """Tracks dispatched-but-uncollected chunk programs on the drive
    thread and accumulates the windows where NO program was in flight —
    the ``gordo_build_device_idle_seconds`` series.

    This is a host-side LOWER bound on true device idle (the device may
    also starve while a dispatched program's inputs stream — only device
    profiling sees that), but it is exactly the quantity the
    dispatch/collect split exists to shrink: serial drives count every
    between-chunk fetch/assemble/write gap as idle; the pipelined drive
    should count little beyond the first chunk's load."""

    def __init__(self):
        self._inflight = 0
        self._idle_since: Optional[float] = time.time()
        self.idle_seconds = 0.0

    def dispatched(self) -> None:
        if self._inflight == 0 and self._idle_since is not None:
            dt = time.time() - self._idle_since
            self.idle_seconds += dt
            _PIPE_DEVICE_IDLE_SECONDS.inc(dt)
            self._idle_since = None
        self._inflight += 1
        _PIPE_DEVICE_INFLIGHT.set(float(self._inflight))

    def collected(self) -> None:
        self._inflight -= 1
        if self._inflight == 0:
            self._idle_since = time.time()
        _PIPE_DEVICE_INFLIGHT.set(float(self._inflight))


@dataclasses.dataclass
class _PendingChunk:
    """One chunk between its dispatch and its collect: the in-flight
    :class:`~gordo_tpu.parallel.anomaly.PendingFleetBuild` plus everything
    the finish side needs (the loaded arrays stay referenced here so a
    collect-time failure can still demote to singles and free them).
    Warm-start chunks build synchronously inside dispatch (the parity
    gate must read results before deciding on in-chunk cold rebuilds), so
    they arrive with ``detectors`` already set and ``pending`` None."""

    key: Tuple
    ok_chunk: List[Machine]
    loaded: Dict[str, Tuple]
    t0: float
    pending: Optional[Any] = None
    detectors: Optional[List[Any]] = None


def _as_machine(m: Union[Machine, Dict[str, Any]]) -> Machine:
    if isinstance(m, Machine):
        return m
    return Machine.from_config(m)


def _demote_to_single(
    m: Machine,
    singles: List[Machine],
    machine_keys: Dict[str, str],
    key_extra: Optional[Dict[str, Any]],
    demoted: set,
) -> None:
    """Route a fleet-intended machine to the single builder.  The single
    path trains on FULL untruncated data, so if an aligned build keyed this
    machine with the alignment component, the key must drop it — otherwise
    a later aligned run would cache-hit an artifact that never truncated.
    ``demoted`` marks the machine so the singles pass re-checks the cache
    under the rewritten key (a deterministic demotion — e.g. a provider
    whose widths never match config — would otherwise retrain every run)."""
    if key_extra:
        machine_keys[m.name] = calculate_model_key(
            m.name, m.model, m.dataset, m.metadata, extra=None
        )
        demoted.add(m.name)
    singles.append(m)


def _config_widths(dataset_cfg: Dict[str, Any]) -> Optional[Tuple[int, int]]:
    """(n_features, n_outputs) derivable from the dataset CONFIG alone, or
    None — the streaming pipeline buckets machines before any data loads."""
    tags = dataset_cfg.get("tag_list") or dataset_cfg.get("tags")
    if not tags:
        return None
    targets = dataset_cfg.get("target_tag_list") or tags
    return len(tags), len(targets)


def build_project(
    machines: Sequence[Union[Machine, Dict[str, Any]]],
    output_dir: str,
    model_register_dir: Optional[str] = None,
    mesh: Optional[Mesh] = None,
    replace_cache: bool = False,
    max_bucket_size: Optional[int] = None,
    data_workers: Optional[int] = None,
    align_lengths: Optional[int] = None,
    pad_lengths: Optional[int] = None,
    auto_pad: bool = True,
    auto_pad_budget_seconds: Optional[float] = None,
    shard: Optional[Any] = None,
    pipeline: Optional[bool] = None,
    artifact_format: Optional[str] = None,
    warm_start: bool = False,
    ingest: Optional[bool] = None,
) -> ProjectBuildResult:
    """Build every machine; fleet-bucket the homogeneous ones.

    ``warm_start=True`` is the incremental-refresh mode (v2 only —
    requires an existing pack index): pass the SUBSET of machines to
    rebuild, and each one's previous-generation params resolve via
    zero-copy :class:`~gordo_tpu.artifacts.PackStore` reads to seed a
    reduced-epoch warm fit (``GORDO_REFRESH_EPOCH_FRACTION`` of the
    configured epochs).  A per-machine parity gate — the warm final
    training loss must stay within ``GORDO_REFRESH_PARITY_FACTOR`` of
    the previous artifact's — demotes failing machines to a full cold
    rebuild, attested in ``result.warm_fallbacks`` and the machine's
    metadata.  Rebuilt machines already in the index publish through
    ``artifacts.delta_write`` (in-place slot rewrites + one atomic
    index swap that stamps its own generation), so live servers
    delta-reload exactly the touched packs; the config-hash cache is
    bypassed (the configs haven't changed — the data has).

    ``artifact_format``: ``"v1"`` writes the historical one-directory-
    per-machine layout; ``"v2"`` writes one memory-mapped parameter pack
    per fleet chunk (``gordo_tpu/artifacts/``) — the writer stage emits
    ONE pack + index update per (signature, bucket) chunk instead of
    per-machine pickles, the registry records pack refs, and the server
    loads each pack with a single whole-pack device transfer.  Machines
    on the single-machine fallback path still write v1 dirs (the mixed
    layout every reader handles).  Default: ``GORDO_ARTIFACT_FORMAT``,
    else v2 (``GORDO_ARTIFACT_FORMAT=v1`` is the per-machine-dirs escape
    hatch).

    Streaming and memory-bounded: at most TWO chunks of machines
    (2 x the effective bucket size) have arrays resident — the one
    training on device and the one the loader pool is prefetching behind
    it.

    ``pipeline`` (default: env-controlled, on): drive the chunks as a
    three-stage pipeline — loader pool (prefetch) ∥ device (this thread)
    ∥ background artifact-writer pool — so dataset loads and
    ``serializer.dump`` both overlap device compute instead of sitting on
    the critical path.  Artifacts are written to a scratch dir and
    atomically renamed into place; completion records (registry, shard
    state) follow the rename, and the writer queue drains before the
    resumable exit-75 path transitions the shard state.  Artifact bytes
    and registry entries are identical to the serial path's.
    ``GORDO_BUILD_PIPELINE=off`` (kill switch) or ``pipeline=False``
    preserves the serial drive loop; an explicit argument beats the env.

    ``max_bucket_size=None`` (the default) picks a per-signature chunk
    size: ``DEFAULT_MAX_BUCKET`` (512) for dense signatures,
    ``DEFAULT_MAX_BUCKET_LSTM`` for recurrent ones (see
    :func:`default_bucket_size`); an explicit value applies to every
    bucket.

    ``align_lengths``: truncate each fleet-bucketed machine's train rows
    DOWN to a multiple of this (dropping the oldest rows) before training.
    Exact CV parity holds per distinct row count, so a ragged project —
    the normal case once row filtering bites — pays one full XLA compile
    per distinct length (~14s each measured); alignment collapses
    ~``align_lengths`` lengths into one.  The cost is explicit and
    bounded: up to ``align_lengths - 1`` of the OLDEST rows per machine.
    Off (None) by default — results then match the single-machine build
    of the unmodified data exactly.

    ``pad_lengths``: the zero-data-loss alternative — pad each machine's
    rows UP to a multiple of this with weight-masked rows instead of
    truncating (``parallel.anomaly._padded_fleet_program``).  Every real
    row trains and a ragged bucket compiles one program per ALIGNED
    length, but CV fold boundaries and minibatch geometry derive from the
    padded length, so results for not-already-aligned machines differ
    slightly from their single-machine builds (see ``docs/fleet.md``).
    Mutually exclusive with ``align_lengths``.

    ``auto_pad`` (default on): when NEITHER ragged strategy is chosen and
    the config-level estimator (the one behind ``workflow plan``'s
    warning) predicts more than ``auto_pad_budget_seconds`` (default
    :data:`DEFAULT_AUTO_PAD_BUDGET_SECONDS`) of per-distinct-length
    compiles, enable ``pad_lengths=DEFAULT_AUTO_PAD_LENGTHS`` — loudly
    logged, recorded in ``result.auto_pad``, disabled with
    ``auto_pad=False`` (CLI ``--no-auto-pad``).  The selected value flows
    into cache keys exactly as an explicit ``pad_lengths`` would, so the
    decision is stable across re-runs of the same config set.

    ``ingest`` (default: env-controlled via ``GORDO_INGEST``, on): load
    each fleet chunk through the build-ingest plane
    (:func:`gordo_tpu.ingest.plane.load_chunk`) — one fingerprint-deduped,
    fleet-vectorized columnar assembly per chunk instead of one
    ``dataset.get_data()`` pandas pass per machine, writing straight into
    the stacked ``(m_pad, n, tags)`` buffer the dispatch path adopts.
    Byte-identical artifacts either way (tests/test_ingest.py);
    ``GORDO_INGEST=off`` or ``ingest=False`` restores the per-machine
    loader pool.

    ``data_workers`` (default None → adaptive): loader-pool threads.
    BENCH_r23 measured the fixed 8-thread pool SLOWER than serial loading
    on a low-core host (GIL contention on pure-pandas work), so None now
    sizes the pool to the host — and to the ingest plane, whose unit of
    work is a whole chunk, not a machine.  The resolved value lands in
    ``result.loader_workers``.

    ``shard``: a :class:`gordo_tpu.distributed.partition.ProcessShard` —
    build only this process's slice of ``machines`` (multi-host builds;
    artifact/metadata layout is identical to the single-host path).  The
    shard's state file tracks per-machine completion so a killed worker's
    shard is resumable.

    Returns a :class:`ProjectBuildResult` with one artifact dir per machine
    (identical layout to ``provide_saved_model``).
    """
    t_start = time.time()
    from gordo_tpu.utils.compile_cache import enable_persistent_compile_cache

    enable_persistent_compile_cache()
    if align_lengths is not None and align_lengths < 2:
        raise ValueError(
            f"align_lengths must be >= 2 (got {align_lengths}); it is a "
            "row-count multiple, and 0/1/negative would change cache "
            "identity without changing any training data"
        )
    if pad_lengths is not None and pad_lengths < 2:
        raise ValueError(
            f"pad_lengths must be >= 2 (got {pad_lengths}); it is a "
            "row-count multiple"
        )
    if align_lengths and pad_lengths:
        raise ValueError(
            "align_lengths (truncate down) and pad_lengths (pad up) are "
            "mutually exclusive — pick one ragged-fleet strategy"
        )
    machines = [_as_machine(m) for m in machines]
    result = ProjectBuildResult()
    artifact_fmt = artifacts.resolve_format(artifact_format)
    result.artifact_format = artifact_fmt
    use_ingest = ingest_plane.resolve_enabled(ingest)
    if data_workers is None:
        # adaptive pool sizing (see docstring): the ingest plane loads a
        # whole chunk per task, so prefetch depth (2: current + next) is
        # all the parallelism the pipeline can use; the per-machine path
        # scales with cores but never past the old fixed 8
        ncpu = os.cpu_count() or 2
        data_workers = 2 if use_ingest else max(2, min(8, ncpu - 1))
    result.loader_workers = int(data_workers)
    result.ingest = {"enabled": use_ingest} if use_ingest else None
    tracker = _LoadTracker()
    occupancy = _DeviceOccupancy()
    warm_resolved: Dict[str, Tuple[Any, Optional[float]]] = {}
    #: per-machine warm-start attestation, stamped into artifact metadata
    warm_info_by_name: Dict[str, Dict[str, Any]] = {}
    if warm_start:
        if artifact_fmt != "v2":
            raise ValueError(
                "warm_start=True needs the v2 pack layout (previous "
                "params resolve through the pack index) — rebuild with "
                "artifact_format='v2' or drop warm_start"
            )
        # a drifted machine's CONFIG is unchanged — its data drifted — so
        # the config-hash cache would skip the very rebuild we were asked
        # for; warm builds always retrain
        replace_cache = True
        warm_resolved = _resolve_warm_params(
            output_dir, [m.name for m in machines]
        )
        if not warm_resolved:
            logger.warning(
                "warm_start=True but no previous params resolved under "
                "%s — every machine rebuilds cold", output_dir,
            )
    # the auto-pad decision runs over the FULL machine list, before any
    # shard filtering: every process of a multi-host build (and a later
    # single-host re-run of the same config) must reach the same ragged
    # strategy, or cache keys would diverge across shards
    if auto_pad and align_lengths is None and pad_lengths is None:
        budget = (
            DEFAULT_AUTO_PAD_BUDGET_SECONDS
            if auto_pad_budget_seconds is None
            else auto_pad_budget_seconds
        )
        bill = estimate_ragged_compile_seconds(machines)
        if bill > budget:
            pad_lengths = DEFAULT_AUTO_PAD_LENGTHS
            result.auto_pad = pad_lengths
            logger.warning(
                "AUTO-PAD: configs predict ~%.0fs of per-distinct-length "
                "XLA compiles (> %.0fs budget) — enabling "
                "pad_lengths=%d (zero data loss; CV fold/batch geometry "
                "derives from the padded length, see docs/fleet.md). "
                "Pass --no-auto-pad (auto_pad=False) for exact-parity "
                "mode, or choose --align-lengths/--pad-lengths "
                "explicitly.",
                bill, budget, pad_lengths,
            )

    shard_state = None
    if shard is not None:
        # multi-host: restrict to this process's slice (order preserved);
        # the partition is machine-name based so the same project config
        # yields the same shard in every process
        wanted = set(shard.names)
        machines = [m for m in machines if m.name in wanted]
        result.shard = (shard.process_id, shard.num_processes)
        shard_state = getattr(shard, "state", None)
        if shard_state is not None:
            shard_state.start([m.name for m in machines])

    _done_lock = threading.Lock()

    def _done(name: str) -> None:
        """A machine needs no further work (artifact on disk or cached).
        Serialized: the writer pool and the drive loop both record."""
        if shard_state is not None:
            with _done_lock:
                shard_state.record(name)
    # alignment/padding changes what data trains (or how it is batched and
    # folded), so it must be part of the cache identity — otherwise an
    # aligned build silently reuses full-parity artifacts (and vice
    # versa).  Only FLEET-built machines align/pad; config-determined
    # singles train on full data and therefore key WITHOUT the component.
    key_extra = None
    if align_lengths:
        key_extra = {"align_lengths": align_lengths}
    elif pad_lengths:
        key_extra = {"pad_lengths": pad_lengths}

    # 1. Fleetability from CONFIG alone (no data loaded yet) + the
    #    config-hash cache check (reference: provide_saved_model) with the
    #    key matching what each machine's path will actually train on.
    #    When no alignment is in play the key can't depend on fleetability,
    #    so the (near-free) registry lookup runs FIRST and cache-hit
    #    machines skip model analysis entirely — a fully-cached project
    #    re-run must not instantiate 10k pipelines.
    def _analyze(m: Machine):
        cv_mode = m.evaluation.get("cv_mode", "full_build")
        widths = _config_widths(m.dataset)
        spec = None
        if cv_mode == "full_build" and widths is not None:
            try:
                spec = analyze_definition(
                    serializer.from_definition(dict(m.model))
                )
            except Exception:
                spec = None
        if spec is None and widths is None and cv_mode == "full_build":
            # this machine may be paying for its config: without an
            # explicit tag_list the stream can't bucket it pre-load, so it
            # loses the stacked-XLA path — say so
            logger.warning(
                "Machine %s has no tag_list/tags in its dataset config; "
                "building single (fleet bucketing needs config-derivable "
                "widths)", m.name,
            )
        return spec, widths

    def _lookup(key: str, m: Machine) -> bool:
        if model_register_dir and not replace_cache:
            cached = lookup_cached_artifact(model_register_dir, key, m.name)
            if cached is not None:
                result.artifacts[m.name] = cached
                result.cached.append(m.name)
                _BUILD_MACHINES_TOTAL.inc(1.0, "cached")
                _done(m.name)
                return True
        return False

    buckets: Dict[Tuple, List[Machine]] = {}
    singles: List[Machine] = []
    specs: Dict[Tuple, Any] = {}
    machine_keys: Dict[str, str] = {}
    demoted: set = set()
    for m in machines:
        if key_extra is None:
            key = calculate_model_key(m.name, m.model, m.dataset, m.metadata)
            machine_keys[m.name] = key
            if _lookup(key, m):
                continue
            spec, widths = _analyze(m)
        else:
            # alignment: try the aligned key FIRST — fleetability is a
            # deterministic function of the configs already hashed into
            # the key, so an aligned-key hit can only be a fleet-aligned
            # artifact, and cache-hit machines skip model analysis here
            # too.  Only on miss do we analyze and, for non-fleetable
            # machines, retry under the unaligned key they build with.
            key = calculate_model_key(
                m.name, m.model, m.dataset, m.metadata, extra=key_extra
            )
            machine_keys[m.name] = key
            if _lookup(key, m):
                continue
            spec, widths = _analyze(m)
            if spec is None:
                key = calculate_model_key(
                    m.name, m.model, m.dataset, m.metadata
                )
                machine_keys[m.name] = key
                if _lookup(key, m):
                    continue
        if spec is None:
            singles.append(m)
            continue
        bkey = (spec.signature, widths, str(m.evaluation.get("cv")))
        buckets.setdefault(bkey, []).append(m)
        specs[bkey] = spec

    # 3. Chunk plan across all buckets, then stream: load chunk k+1 in the
    #    pool while chunk k trains; free arrays as artifacts dump.
    chunks: List[Tuple[Tuple, List[Machine]]] = []
    for key, bucket in buckets.items():
        size = max_bucket_size or default_bucket_size(specs[key])
        for start in range(0, len(bucket), size):
            chunks.append((key, bucket[start : start + size]))

    def _load(m: Machine):
        t0 = time.time()
        dataset = GordoBaseDataset.from_dict(dict(m.dataset))
        X, y = dataset.get_data()
        X = np.asarray(X, np.float32)
        y = np.asarray(y, np.float32)
        if align_lengths and len(X) >= align_lengths:  # validated >= 2
            keep = (len(X) // align_lengths) * align_lengths
            # newest rows win: industrial sensor history is trained most-
            # recent-first relevant, so the truncation drops the head
            X, y = X[len(X) - keep:], y[len(y) - keep:]
        _DATA_LOAD_SECONDS.observe(time.time() - t0)
        _PIPE_STAGE_SECONDS.observe(time.time() - t0, "load")
        entry = (X, y, dataset.get_metadata(), time.time() - t0)
        tracker.acquire()  # arrays are live from here until freed
        return entry

    def _load_chunk_ingest(chunk: List[Machine]) -> Dict[str, Any]:
        """One loader-pool task per CHUNK: the build-ingest plane's
        fingerprint-deduped, fleet-vectorized assembly
        (gordo_tpu/ingest/plane.py).  The capacity callable hands the
        dispatch plane's model-axis padding down so the stacked buffer
        the plane fills IS the ``(m_pad, n, tags)`` array the fleet
        program stages — no re-stack, no pad copy."""
        t0 = time.time()
        entries = ingest_plane.load_chunk(
            chunk,
            align_lengths=align_lengths,
            capacity=(lambda mm: _model_axis_pad(mm, mesh)),
            stats=result.ingest,
        )
        _PIPE_STAGE_SECONDS.observe(time.time() - t0, "load")
        return entries

    def _submit(pool, chunk: List[Machine]):
        if use_ingest:
            return pool.submit(_load_chunk_ingest, chunk)
        return {m.name: pool.submit(_load, m) for m in chunk}

    def _collect(chunk: List[Machine], futures) -> Dict[str, Tuple]:
        loaded: Dict[str, Tuple] = {}
        if use_ingest:
            try:
                entries = futures.result()
            except Exception as exc:  # plane crash: fail the whole chunk
                logger.exception("Ingest load failed for %d machine(s)",
                                 len(chunk))
                for m in chunk:
                    result.failed[m.name] = f"data: {exc}"
                    _BUILD_MACHINES_TOTAL.inc(1.0, "failed")
                return loaded
            for m in chunk:
                entry = entries.get(m.name)
                if entry is None or isinstance(entry, Exception):
                    exc = entry if entry is not None else RuntimeError(
                        "ingest plane produced no entry"
                    )
                    logger.error("Data load failed for %s: %s", m.name, exc)
                    result.failed[m.name] = f"data: {exc}"
                    _BUILD_MACHINES_TOTAL.inc(1.0, "failed")
                    continue
                _DATA_LOAD_SECONDS.observe(entry[3])
                tracker.acquire()  # arrays live until freed, as in _load
                loaded[m.name] = entry
            return loaded
        for m in chunk:
            try:
                loaded[m.name] = futures[m.name].result()
            except Exception as exc:  # data failure must not sink the fleet
                logger.exception("Data load failed for %s", m.name)
                result.failed[m.name] = f"data: {exc}"
                _BUILD_MACHINES_TOTAL.inc(1.0, "failed")
        return loaded

    def _free(loaded: Dict[str, Tuple], names: Sequence[str]) -> None:
        n = 0
        for name in list(names):
            if loaded.pop(name, None) is not None:
                n += 1
        if n:
            tracker.release(n)

    #: warmup-manifest entries, one per successfully fleet-built chunk —
    #: the (signature, bucket) record the serve plane pre-compiles from
    manifest_entries: List[Dict[str, Any]] = []

    def _record_manifest(key: Tuple, ok_chunk: List[Machine]) -> None:
        spec = specs[key]
        widths = key[1]
        manifest_entries.append(
            {
                "signature": hashlib.md5(
                    repr(spec.signature).encode()
                ).hexdigest()[:16],
                "machines": [m.name for m in ok_chunk],
                "n_machines": len(ok_chunk),
                "n_features": int(widths[0]),
                "n_outputs": int(widths[1]),
                "lookback": int(
                    getattr(spec.estimator_proto, "lookback_window", 1) or 1
                ),
                # sizes the streaming plane's carried ring
                # (offset + max(smooth_window, 1) rows)
                "smooth_window": int(
                    getattr(spec.detector_proto, "window", 0) or 0
                ),
            }
        )

    def _note_fallback(name: str, reason: str) -> None:
        """A warm_start machine rebuilding cold: attest why (result +
        metadata) — the bench parity gate accepts an attested fallback."""
        result.warm_fallbacks[name] = reason
        warm_info_by_name[name] = {"warm": False, "fallback": reason}
        logger.warning("warm-start fallback for %s: %s", name, reason)

    def _train_chunk(spec_obj, cv, ok_chunk, loaded, warm_list=None):
        builder = FleetDiffBuilder(
            spec_obj, cv=cv, mesh=mesh, pad_lengths=pad_lengths
        )
        with profiling.trace(f"fleet_bucket/{len(ok_chunk)}"):
            return builder.build(
                [loaded[m.name][0] for m in ok_chunk],
                [loaded[m.name][1] for m in ok_chunk],
                warm_params=warm_list,
            )

    def _dispatch_chunk(spec_obj, cv, ok_chunk, loaded):
        """Async half of _train_chunk (cold builds): launch the chunk's
        fleet program(s), return the pending handle without blocking.
        Part of the lint-enforced D2H-free dispatch window."""
        builder = FleetDiffBuilder(
            spec_obj, cv=cv, mesh=mesh, pad_lengths=pad_lengths
        )
        with profiling.trace(f"fleet_dispatch/{len(ok_chunk)}"):
            return builder.dispatch(
                [loaded[m.name][0] for m in ok_chunk],
                [loaded[m.name][1] for m in ok_chunk],
            )

    def _build_chunk_warm(spec, cv, ok_chunk, loaded):
        """One chunk in warm_start mode: machines with resolved previous
        params run the warm program under a reduced-epoch config, the
        parity gate demotes stragglers, and everything else (plus gate
        failures) rebuilds cold — all within the chunk, so the caller
        still sees detectors in ``ok_chunk`` order."""
        warm_ms = [m for m in ok_chunk if m.name in warm_resolved]
        cold_names = set()
        for m in ok_chunk:
            if m.name not in warm_resolved:
                _note_fallback(m.name, "no-previous-params")
                cold_names.add(m.name)
        dets: Dict[str, Any] = {}
        if warm_ms:
            parity_factor = _refresh_parity_factor()
            warm_cfg = dataclasses.replace(
                spec.train_cfg, epochs=_warm_epochs(spec.train_cfg)
            )
            warm_spec = dataclasses.replace(spec, train_cfg=warm_cfg)
            try:
                warm_dets = _train_chunk(
                    warm_spec, cv, warm_ms, loaded,
                    warm_list=[warm_resolved[m.name][0] for m in warm_ms],
                )
            except Exception:
                logger.exception(
                    "warm-start chunk build failed; rebuilding %d "
                    "machine(s) cold", len(warm_ms),
                )
                for m in warm_ms:
                    _note_fallback(m.name, "warm-build-failed")
                    cold_names.add(m.name)
                warm_ms, warm_dets = [], []
            for m, det in zip(warm_ms, warm_dets):
                prev_loss = warm_resolved[m.name][1]
                hist = np.asarray(
                    getattr(_detector_estimator(det), "history_", ())
                ).ravel()
                warm_loss = float(hist[-1]) if hist.size else float("nan")
                passed = np.isfinite(warm_loss) and (
                    prev_loss is None
                    or warm_loss
                    <= parity_factor * max(prev_loss, 1e-12) + 1e-12
                )
                if passed:
                    dets[m.name] = det
                    result.warm_started.append(m.name)
                    warm_info_by_name[m.name] = {
                        "warm": True,
                        "epochs": int(warm_cfg.epochs),
                        "final_loss": warm_loss,
                        "previous_final_loss": prev_loss,
                    }
                else:
                    _note_fallback(
                        m.name,
                        f"parity: warm final loss {warm_loss:.6g} vs "
                        f"previous {prev_loss} "
                        f"(factor {parity_factor:g})",
                    )
                    cold_names.add(m.name)
        cold_ms = [m for m in ok_chunk if m.name in cold_names]
        if cold_ms:
            for m, det in zip(cold_ms, _train_chunk(spec, cv, cold_ms,
                                                    loaded)):
                dets[m.name] = det
        return [dets[m.name] for m in ok_chunk]

    def _dispatch_bucket(
        key: Tuple, chunk: List[Machine], loaded: Dict[str, Tuple]
    ) -> Optional[_PendingChunk]:
        """Width-validate + DISPATCH one chunk's fleet program(s); returns
        a pending record (or None when every machine demoted).  Cold
        chunks return with device futures only — the blocking fetch lives
        in ``_finish_bucket`` — so the caller can dispatch chunk k+1
        before finishing chunk k.  Warm-start chunks run synchronously
        here (see :class:`_PendingChunk`).  Lint-enforced D2H-free zone
        alongside ``_drive_pipeline`` (scripts/lint.py)."""
        spec = specs[key]
        widths = key[1]
        # config said these widths; data disagreeing (exotic provider)
        # reroutes the machine through the single builder
        ok_chunk = []
        for m in chunk:
            if m.name not in loaded:
                continue
            X, y = loaded[m.name][0], loaded[m.name][1]
            if (X.shape[1], y.shape[1]) != widths:
                logger.warning(
                    "Machine %s loaded widths %s != config %s; "
                    "building single", m.name, (X.shape[1], y.shape[1]),
                    widths,
                )
                _demote_to_single(
                    m, singles, machine_keys, key_extra, demoted
                )
                _free(loaded, [m.name])
            else:
                ok_chunk.append(m)
        if not ok_chunk:
            return None
        cv = ok_chunk[0].evaluation.get("cv")
        t0 = time.time()
        if warm_start:
            occupancy.dispatched()
            try:
                detectors = _build_chunk_warm(spec, cv, ok_chunk, loaded)
            except Exception:
                logger.exception(
                    "Fleet bucket failed; falling back to singles"
                )
                for m in ok_chunk:
                    _demote_to_single(
                        m, singles, machine_keys, key_extra, demoted
                    )
                _free(loaded, [m.name for m in ok_chunk])
                return None
            finally:
                occupancy.collected()
            return _PendingChunk(
                key=key, ok_chunk=ok_chunk, loaded=loaded, t0=t0,
                detectors=detectors,
            )
        try:
            pending = _dispatch_chunk(spec, cv, ok_chunk, loaded)
        except Exception:
            # host-side failure (trace/compile/stacking) — async XLA
            # failures surface at collect and demote in _finish_bucket
            logger.exception("Fleet dispatch failed; falling back to singles")
            for m in ok_chunk:
                _demote_to_single(
                    m, singles, machine_keys, key_extra, demoted
                )
            _free(loaded, [m.name for m in ok_chunk])
            return None
        occupancy.dispatched()
        _PIPE_STAGE_SECONDS.observe(time.time() - t0, "dispatch")
        return _PendingChunk(
            key=key, ok_chunk=ok_chunk, loaded=loaded, t0=t0,
            pending=pending,
        )

    def _finish_bucket(rec: _PendingChunk):
        """Collect one dispatched chunk: blocking D2H fetch + per-machine
        assembly.  An async failure from dispatch surfaces here and
        demotes the chunk to singles, exactly like the serial path's
        train-time failures.  Returns ``(ok_chunk, detectors,
        fleet_seconds)`` or None."""
        ok_chunk, loaded = rec.ok_chunk, rec.loaded
        detectors = rec.detectors
        if rec.pending is not None:
            try:
                with profiling.trace(f"fleet_collect/{len(ok_chunk)}"):
                    detectors = rec.pending.collect()
            except Exception:
                logger.exception(
                    "Fleet bucket failed; falling back to singles"
                )
                for m in ok_chunk:
                    _demote_to_single(
                        m, singles, machine_keys, key_extra, demoted
                    )
                _free(loaded, [m.name for m in ok_chunk])
                return None
            finally:
                occupancy.collected()
            _PIPE_STAGE_SECONDS.observe(rec.pending.fetch_seconds, "fetch")
            _PIPE_STAGE_SECONDS.observe(
                rec.pending.assemble_seconds, "assemble"
            )
        fleet_seconds = time.time() - rec.t0
        _BUILD_BUCKET_SECONDS.observe(fleet_seconds)
        _PIPE_STAGE_SECONDS.observe(fleet_seconds, "device")
        return ok_chunk, detectors, fleet_seconds

    def _finish_chunk(rec: _PendingChunk, writer: Optional[_ArtifactWriter]):
        """Finish one chunk end-to-end: collect, manifest, and hand the
        artifacts to the writer pool (pipelined) or write them inline
        (serial, ``writer=None``)."""
        key = rec.key
        out = _finish_bucket(rec)
        if out is None:
            return
        ok_chunk, detectors, fleet_seconds = out
        loaded = rec.loaded
        _record_manifest(key, ok_chunk)
        _PIPE_CHUNKS_TOTAL.inc(1.0, "pipelined" if writer else "serial")
        if artifact_fmt == "v2":
            payload = _chunk_payload(ok_chunk, detectors, fleet_seconds,
                                     loaded, rec.pending)
            if writer is not None:
                # v2: the chunk IS the write unit — one pack per chunk
                # rides the writer queue as a single item
                writer.submit([payload])
            else:
                _write_chunk(*payload)
            return
        per_machine = fleet_seconds / len(ok_chunk)
        if writer is None:
            baselines = _chunk_baselines(ok_chunk, detectors, loaded,
                                         rec.pending)
            for m, det in zip(ok_chunk, detectors):
                _dump_machine(
                    m,
                    det,
                    loaded[m.name],
                    per_machine,
                    output_dir,
                    model_register_dir,
                    result,
                    fleet=True,
                    align_lengths=align_lengths,
                    pad_lengths=pad_lengths,
                    cache_key=machine_keys[m.name],
                    baseline=baselines.get(m.name),
                )
                _done(m.name)
                _free(loaded, [m.name])  # artifact on disk: arrays drop
            return
        # machines in a chunk share ONE model config, so their
        # definition.yaml bytes are identical by construction —
        # serialize once per chunk instead of per machine (the
        # byte-parity test pins pipelined == serial per machine, so
        # a config that DID diverge inside a chunk would be caught)
        chunk_definition = serializer.render_definition(detectors[0])
        baselines = _chunk_baselines(ok_chunk, detectors, loaded,
                                     rec.pending)
        batch = []
        for m, det in zip(ok_chunk, detectors):
            metadata = _machine_metadata(
                m,
                det,
                loaded[m.name],
                per_machine,
                fleet=True,
                align_lengths=align_lengths,
                pad_lengths=pad_lengths,
                cache_key=machine_keys[m.name],
                baseline=baselines.get(m.name),
            )
            _free(loaded, [m.name])  # arrays drop at enqueue, not write
            batch.append(
                (m.name, det, metadata, per_machine, chunk_definition)
            )
        writer.submit(batch)  # one handoff per chunk

    def _drive_serial(pool) -> None:
        """The pre-pipeline drive loop (GORDO_BUILD_PIPELINE=off): loads
        still prefetch one chunk ahead, but dispatch and collect run back
        to back (no overlap) and artifact dumps run inline on the
        critical path after each chunk trains."""
        next_futures = _submit(pool, chunks[0][1]) if chunks else None
        for i, (key, chunk) in enumerate(chunks):
            loaded = _collect(chunk, next_futures)
            # prefetch the NEXT chunk now — it loads while this one trains
            next_futures = (
                _submit(pool, chunks[i + 1][1]) if i + 1 < len(chunks) else None
            )
            rec = _dispatch_bucket(key, chunk, loaded)
            if rec is not None:
                _finish_chunk(rec, None)

    def _drive_pipeline(pool, writer: _ArtifactWriter) -> None:
        """The pipelined drive loop: loader pool (stage A, prefetching) ∥
        device stage B split into DISPATCH and COLLECT halves on this
        thread ∥ artifact-writer pool (stage C).

        Stage B's split is the r23 overlap: chunk k+1's program
        dispatches (async H2D staging through the placement seam + jax
        async dispatch) BEFORE chunk k's blocking fetch/assembly runs, so
        the host-side collect work of chunk k hides behind chunk k+1's
        device compute instead of starving the device between chunks.
        Loads for chunk k+2 submit only after chunk k's arrays free,
        preserving the 2-chunk peak_loaded bound.  Metadata assembles at
        enqueue time so the chunk's arrays free BEFORE the write queues
        (the bound holds regardless of writer backlog).  This function is
        a D2H-free zone — ``scripts/lint.py`` rejects blocking
        device→host calls (jax.device_get / np.asarray / to_host /
        block_until_ready) in its body; the D2H lives in
        ``_finish_bucket`` via ``PendingFleetBuild.collect``."""
        if not chunks:
            return
        futures = _submit(pool, chunks[0][1])
        prev: Optional[_PendingChunk] = None
        for i, (key, chunk) in enumerate(chunks):
            t_wait = time.time()
            loaded = _collect(chunk, futures)
            _PIPE_STALL_SECONDS.inc(time.time() - t_wait, "load")
            rec = _dispatch_bucket(key, chunk, loaded)
            if prev is not None:
                _finish_chunk(prev, writer)  # overlaps chunk i's compute
            prev = rec
            futures = (
                _submit(pool, chunks[i + 1][1]) if i + 1 < len(chunks) else None
            )
        if prev is not None:
            _finish_chunk(prev, writer)

    use_pipeline = _pipeline_enabled(pipeline) and bool(chunks)
    result.pipelined = use_pipeline
    tmp_root = os.path.join(output_dir, ".gordo-tmp")
    writer: Optional[_ArtifactWriter] = None

    def _write_one(name: str, det, metadata: Dict[str, Any],
                   per_machine: float,
                   definition: Optional[str] = None) -> None:
        """Writer-pool task: atomic artifact write + completion records.
        Failures fail ONE machine (recorded loudly), never the drain."""
        try:
            dest = os.path.join(output_dir, name)
            _write_artifact(
                det, metadata, dest, model_register_dir,
                metadata.get("cache_key"), tmp_root=tmp_root,
                definition=definition,
            )
        except Exception as exc:
            logger.exception("Artifact write failed for %s", name)
            result.failed[name] = f"write: {exc}"
            _BUILD_MACHINES_TOTAL.inc(1.0, "failed")
            return
        result.artifacts[name] = dest
        result.fleet_built.append(name)
        _BUILD_MACHINES_TOTAL.inc(1.0, "fleet")
        _BUILD_MACHINE_SECONDS.observe(per_machine, "fleet")
        _done(name)

    def _chunk_payload(ok_chunk, detectors, fleet_seconds, loaded,
                       pending=None) -> Tuple:
        """Assemble a v2 chunk's write payload (metadata closes over the
        training arrays, so they free HERE — at enqueue — keeping the
        2-chunk peak_loaded bound independent of writer backlog).
        Fleet-health baselines sketch FIRST, while the chunk's training
        arrays are still resident — one stacked scoring dispatch for the
        whole chunk (telemetry.fleet_health.training_baselines), fed the
        collect side's stacked arrays so nothing restacks."""
        per_machine = fleet_seconds / len(ok_chunk)
        chunk_definition = serializer.render_definition(detectors[0])
        baselines = _chunk_baselines(ok_chunk, detectors, loaded, pending)
        metadatas = []
        for m, det in zip(ok_chunk, detectors):
            metadatas.append(_machine_metadata(
                m, det, loaded[m.name], per_machine, fleet=True,
                align_lengths=align_lengths, pad_lengths=pad_lengths,
                cache_key=machine_keys[m.name],
                baseline=baselines.get(m.name),
                warm_info=warm_info_by_name.get(m.name),
            ))
            _free(loaded, [m.name])
        names = [m.name for m in ok_chunk]
        return names, list(detectors), metadatas, per_machine, chunk_definition

    def _record_packed(names, per_machine) -> None:
        """Bookkeeping shared by the pack and delta publish paths."""
        for name in names:
            result.artifacts[name] = artifacts.machine_ref(output_dir, name)
            result.fleet_built.append(name)
            _BUILD_MACHINES_TOTAL.inc(1.0, "fleet")
            _BUILD_MACHINE_SECONDS.observe(per_machine, "fleet")
            _register(
                artifacts.machine_ref(output_dir, name),
                model_register_dir, machine_keys.get(name),
            )
            _done(name)

    def _write_chunk_delta(names, detectors, metadatas, per_machine,
                           definition: Optional[str] = None) -> None:
        """Incremental publish (warm_start builds): machines the pack
        index already knows rewrite their slots in place via
        ``delta_write`` — whose single atomic index swap stamps its own
        generation, so live servers delta-reload exactly the touched
        packs — and machines the index doesn't know yet land as a fresh
        pack row published by the build's final stamp.  A structural
        mismatch (leaf signature changed since the previous generation)
        demotes the whole chunk to a fresh pack; any other write failure
        fails THESE machines loudly and leaves the store on its previous
        healthy generation — no partial-delta limbo, the next refresh
        cycle retries."""
        store = artifacts.open_store(output_dir)
        known = set(store.names()) if store is not None else set()
        delta_names = [n for n in names if n in known]
        fresh_names = [n for n in names if n not in known]
        by_name = dict(zip(names, detectors))
        meta_by_name = dict(zip(names, metadatas))
        try:
            if delta_names:
                try:
                    artifacts.delta_write(
                        output_dir,
                        {n: by_name[n] for n in delta_names},
                        metadatas={n: meta_by_name[n] for n in delta_names},
                    )
                except artifacts.PackError:
                    # structural change since the previous generation —
                    # a delta can't express it; write a fresh pack row
                    logger.warning(
                        "delta publish: leaf signature changed for chunk "
                        "%s...; writing a fresh pack instead", names[:3],
                    )
                    fresh_names = list(names)
                    delta_names = []
            if fresh_names:
                artifacts.write_pack(
                    output_dir, fresh_names,
                    [by_name[n] for n in fresh_names],
                    [meta_by_name[n] for n in fresh_names],
                    definition=definition,
                    cache_keys={
                        n: machine_keys[n]
                        for n in fresh_names if n in machine_keys
                    },
                )
        except Exception as exc:
            logger.exception(
                "Incremental publish failed for chunk %s...", names[:3],
            )
            for name in names:
                result.failed[name] = f"write: {exc}"
                _BUILD_MACHINES_TOTAL.inc(1.0, "failed")
            return
        _record_packed(names, per_machine)

    def _write_chunk_pack(names, detectors, metadatas, per_machine,
                          definition: Optional[str] = None) -> None:
        """v2 writer task: ONE pack + index update per fleet chunk.  A
        pack-level failure falls back to per-machine v1 artifacts — the
        chunk must not lose machines to a packing edge case."""
        try:
            artifacts.write_pack(
                output_dir, names, detectors, metadatas,
                definition=definition,
                cache_keys={
                    n: machine_keys[n] for n in names if n in machine_keys
                },
            )
        except Exception:
            logger.exception(
                "Pack write failed for chunk %s...; falling back to "
                "per-machine artifacts", names[:3],
            )
            for name, det, metadata in zip(names, detectors, metadatas):
                _write_one(name, det, metadata, per_machine, definition)
            return
        _record_packed(names, per_machine)

    # warm_start publishes incrementally (delta_write for known machines)
    # so live servers reload ONLY the touched packs; full builds write
    # whole chunk packs as always
    _write_chunk = _write_chunk_delta if warm_start else _write_chunk_pack

    with ThreadPoolExecutor(max_workers=data_workers) as pool:
        if use_pipeline:
            writer = _ArtifactWriter(
                _write_chunk if artifact_fmt == "v2" else _write_one
            )
            try:
                _drive_pipeline(pool, writer)
            except BaseException:
                writer.drain()
                raise
        else:
            _drive_serial(pool)

    # 4. Single-machine fallback (non-fleetable configs) — one at a time,
    #    each build loading and freeing its own data.
    if singles and (align_lengths or pad_lengths):
        which = (
            f"align_lengths={align_lengths}" if align_lengths
            else f"pad_lengths={pad_lengths}"
        )
        logger.warning(
            "%s does not apply to the %d machine(s) building "
            "through the single-machine path (%s%s): they train on their "
            "full unmodified data",
            which, len(singles),
            ", ".join(m.name for m in singles[:5]),
            "..." if len(singles) > 5 else "",
        )
    for m in singles:
        # a runtime-demoted machine's key was rewritten to the unaligned
        # form; a prior run's single artifact may already satisfy it
        if m.name in demoted and _lookup(machine_keys[m.name], m):
            continue
        if warm_start and m.name not in result.warm_fallbacks:
            # single-path builds have no fleet program to warm-start
            _note_fallback(m.name, "single-path")
        t_single = time.time()
        try:
            model, metadata = build_model(
                m.name, m.model, m.dataset, m.metadata, m.evaluation
            )
        except Exception as exc:
            logger.exception("Single build failed for %s", m.name)
            result.failed[m.name] = f"build: {exc}"
            _BUILD_MACHINES_TOTAL.inc(1.0, "failed")
            continue
        metadata["cache_key"] = machine_keys[m.name]
        dest = os.path.join(output_dir, m.name)
        serializer.dump(model, dest, metadata=metadata)
        _register(dest, model_register_dir, machine_keys[m.name])
        result.artifacts[m.name] = dest
        result.single_built.append(m.name)
        _BUILD_MACHINES_TOTAL.inc(1.0, "single")
        _BUILD_MACHINE_SECONDS.observe(time.time() - t_single, "single")
        _done(m.name)

    if writer is not None:
        # exit-75 / resumable contract: every queued artifact is fully on
        # disk (or its failure recorded) BEFORE the shard state
        # transitions and before this function returns — the singles pass
        # above ran concurrently with the tail of the write queue
        writer.drain()
        shutil.rmtree(tmp_root, ignore_errors=True)

    if artifact_fmt == "v2":
        # ONE atomic generation flip publishes every pending pack row
        # this build wrote — the only reload signal serving replicas act
        # on, so a mid-build index is never mistaken for a new fleet.
        # No-op (returns the current id) when the run was fully cached.
        try:
            generation = artifacts.stamp_generation(output_dir)
            result.generation = generation
            if generation:
                logger.info(
                    "published artifact generation %d", generation
                )
        except Exception:
            logger.exception("generation stamp failed — serving "
                             "replicas will not hot-reload this build")

    if shard_state is not None:
        if result.failed:
            shard_state.mark_resumable(
                f"{len(result.failed)} machine(s) failed"
            )
        else:
            shard_state.finish()
    result.seconds = time.time() - t_start
    result.peak_loaded = tracker.peak
    result.device_idle_seconds = occupancy.idle_seconds
    _write_telemetry_snapshot(output_dir, result.shard)
    try:
        # the (signature, bucket) set this build materialized — what the
        # server (or `gordo warmup`) pre-compiles before going ready.  A
        # fully-cached re-run records nothing and keeps the existing
        # manifest; a partial rebuild merges into it, pruned against the
        # machines that actually exist on disk so a shrunk bucket can't
        # leave stale (signature, bucket) rows behind.
        from gordo_tpu.compile import write_warmup_manifest
        from gordo_tpu.serve.precision import serve_dtype

        write_warmup_manifest(
            output_dir, manifest_entries, shard=result.shard,
            live_machines=(
                artifacts.machines_on_disk(output_dir)
                | set(result.artifacts)
            ),
            # resolved HERE, at build time: the manifest carries the
            # precision this deployment is configured for, so a server
            # started without GORDO_SERVE_DTYPE set still warms and
            # serves what the build intended
            serve_dtype=serve_dtype(),
            # the device mesh the fleet programs compiled over — lets
            # the serve plane (and `gordo mesh info`) see what placement
            # this build warmed for
            mesh=mesh,
        )
    except Exception:  # the manifest is a hint, never a build failure
        logger.exception("warmup manifest write failed")
    return result


def _write_telemetry_snapshot(
    output_dir: str, shard: Optional[Tuple[int, int]]
) -> None:
    """Shard-local metric snapshot under ``<output_dir>/.gordo-telemetry/``
    — one file per process of a (multi-host) build, merged later by
    ``gordo telemetry dump --dir`` / watchman.  Process-id-keyed filenames
    mean a re-run of the same shard overwrites its own snapshot and never
    a peer's."""
    if not telemetry.enabled():
        return
    pid, n = shard or (0, 1)
    path = os.path.join(
        output_dir, telemetry.SNAPSHOT_DIR,
        f"shard-{pid:03d}-of-{n:03d}.json",
    )
    try:
        telemetry.REGISTRY.write_snapshot(path)
    except Exception:  # telemetry must never fail a build
        logger.exception("telemetry snapshot write failed: %s", path)


def _chunk_baselines(ok_chunk, detectors, loaded, pending=None) -> Dict[str, Any]:
    """Training-time residual sketches for a just-trained chunk — ONE
    stacked scoring dispatch over the still-resident training arrays
    (the device-stage cost rides the same thread the chunk trained on,
    like training itself).  ``pending`` (the chunk's collected
    :class:`PendingFleetBuild`, when it built async) re-exposes the
    fetched stacked arrays so the scorer skips its leaf-by-leaf restack
    of the per-machine views.  ``GORDO_FLEET_BASELINE=off`` skips it."""
    from gordo_tpu.telemetry import fleet_health

    hint = (
        pending.prestacked([m.name for m in ok_chunk])
        if pending is not None else None
    )
    return fleet_health.training_baselines(
        {m.name: det for m, det in zip(ok_chunk, detectors)},
        {m.name: loaded[m.name][0] for m in ok_chunk if m.name in loaded},
        prestacked_hint=hint,
    )


def _machine_metadata(
    m: Machine,
    detector,
    loaded_entry: Tuple,
    fit_seconds: float,
    fleet: bool,
    align_lengths: Optional[int] = None,
    pad_lengths: Optional[int] = None,
    cache_key: Optional[str] = None,
    baseline: Optional[Dict[str, Any]] = None,
    warm_info: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Assemble one machine's artifact metadata — everything except the
    disk writes, so the pipelined path can free the training arrays at
    enqueue time and hand the writer pool a closed payload."""
    X, _, dataset_meta, query_seconds = loaded_entry
    metadata = assemble_metadata(
        name=m.name,
        model=detector,
        model_config=m.model,
        data_config=m.dataset,
        dataset_metadata=dataset_meta,
        metadata=m.metadata,
        data_query_duration=query_seconds,
        cv_duration=fit_seconds,  # fleet: CV+fit are one fused program
        fit_duration=fit_seconds,
        cv_meta=getattr(detector, "cv_metadata_", {}),
    )
    metadata["model"]["fleet_built"] = fleet
    if align_lengths:
        # a truncated artifact must be distinguishable from a full-parity
        # one: record the alignment and the row count actually trained on
        metadata["model"]["align_lengths"] = int(align_lengths)
        metadata["model"]["rows_trained"] = int(X.shape[0])
    if pad_lengths and getattr(detector, "pad_built_", False):
        # padded-mode artifact: every real row trained, but fold/batch
        # geometry came from the padded group length.  Machines the
        # builder demoted to the exact path (too short / exotic splitter)
        # do NOT get the stamp — their artifacts are full-parity builds.
        metadata["model"]["pad_lengths"] = int(pad_lengths)
        metadata["model"]["rows_trained"] = int(X.shape[0])
    if warm_info is not None:
        # incremental-refresh attestation: either the warm-start lineage
        # (epochs trained, previous/final loss) or the cold-fallback
        # reason — auditable per machine, per generation
        metadata["model"]["warm_start"] = dict(warm_info)
    # the artifact stamps its own cache identity so a later lookup can
    # detect that this dir was overwritten by a different build
    if cache_key is not None:
        metadata["cache_key"] = cache_key
    if baseline is not None:
        # the training-time residual distribution (fleet-health sketch):
        # the serve plane loads it as the drift-comparison baseline
        metadata["fleet-health"] = {"version": 1, "baseline": baseline}
    return metadata


def _write_artifact(
    detector,
    metadata: Dict[str, Any],
    dest: str,
    model_register_dir: Optional[str],
    cache_key: Optional[str],
    tmp_root: Optional[str] = None,
    definition: Optional[str] = None,
) -> None:
    """Serialize one artifact to ``dest`` and register it.

    ``tmp_root`` set (the pipelined path): the artifact dumps into a
    scratch dir and renames into place — the rename is atomic, so a kill
    mid-write leaves either no dir at ``dest`` or a complete artifact,
    never a partial one.  The registry entry follows the rename.
    ``tmp_root`` None (serial path): in-place dump, the historical
    behavior.  ``definition``: pre-rendered definition.yaml text
    (chunk-shared; see the drive loop).
    """
    if tmp_root is None:
        serializer.dump(detector, dest, metadata=metadata,
                        definition=definition)
    else:
        tmp = os.path.join(
            tmp_root, f"{os.path.basename(dest)}.{uuid.uuid4().hex[:8]}"
        )
        serializer.dump(detector, tmp, metadata=metadata,
                        definition=definition)
        if os.path.isdir(dest):  # rebuild over an existing artifact dir
            shutil.rmtree(dest)
        os.replace(tmp, dest)
    _register(dest, model_register_dir, cache_key)


def _dump_machine(
    m: Machine,
    detector,
    loaded_entry: Tuple,
    fit_seconds: float,
    output_dir: str,
    model_register_dir: Optional[str],
    result: ProjectBuildResult,
    fleet: bool,
    align_lengths: Optional[int] = None,
    pad_lengths: Optional[int] = None,
    cache_key: Optional[str] = None,
    baseline: Optional[Dict[str, Any]] = None,
) -> None:
    """Serial-path artifact dump: metadata + write + bookkeeping inline."""
    metadata = _machine_metadata(
        m, detector, loaded_entry, fit_seconds, fleet=fleet,
        align_lengths=align_lengths, pad_lengths=pad_lengths,
        cache_key=cache_key, baseline=baseline,
    )
    dest = os.path.join(output_dir, m.name)
    _write_artifact(detector, metadata, dest, model_register_dir, cache_key)
    result.artifacts[m.name] = dest
    result.fleet_built.append(m.name)
    _BUILD_MACHINES_TOTAL.inc(1.0, "fleet")
    _BUILD_MACHINE_SECONDS.observe(fit_seconds, "fleet")


def _register(
    dest: str, model_register_dir: Optional[str], key: Optional[str]
) -> None:
    """Registry write under the key computed ONCE in step 1 — the stamp in
    metadata, the registry entry, and the next run's lookup must all agree
    or the overwrite-detection breaks.  v2 pack refs record verbatim (the
    pack index, not a per-machine path, is the unit the registry points
    at); v1 artifact dirs record as absolute paths, as always."""
    if model_register_dir and key:
        value = dest if artifacts.is_pack_ref(dest) else os.path.abspath(dest)
        disk_registry.write_key(model_register_dir, key, value)
