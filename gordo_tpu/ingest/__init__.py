"""Build-ingest plane: fleet-level dataset assembly.

The r23 stage attribution showed the build loop ingest-bound: per
512-machine chunk, the host ``load`` stage (512 sequential per-machine
``dataset.get_data()`` pandas passes) cost more than the device compute
it feeds.  This package is the tf.data move for the fleet builder — keep
the input pipeline off the accelerator's critical path:

- :mod:`gordo_tpu.ingest.fingerprint` — dataset/provider fingerprints,
  hoisted from the r18 backfill runner into the ONE shared definition of
  "these machines fetch the same data" used by the builder, refresh, and
  batch planes.
- :mod:`gordo_tpu.ingest.plane` — :func:`~gordo_tpu.ingest.plane.load_chunk`:
  one chunk of machines assembled as a fleet.  Machines sharing a dataset
  fingerprint fetch once; machines sharing (index, resolution, window)
  geometry resample/join as ONE columnar numpy pass across the machine
  axis, written straight into a preallocated ``(m_pad, n, tags)`` float32
  stacked buffer the dispatch path adopts without re-stacking.  Anything
  the vectorized path cannot express takes the sanctioned per-machine
  ``get_data()`` fallback with byte-identical results.
"""

from gordo_tpu.ingest.fingerprint import (  # noqa: F401
    dataset_fingerprint,
    provider_fingerprint,
)
from gordo_tpu.ingest.plane import (  # noqa: F401
    load_chunk,
    owned_stack_base,
    resolve_enabled,
    stack_live_slots,
)
