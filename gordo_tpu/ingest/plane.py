"""Fleet-vectorized chunk ingest: one columnar pass instead of 512.

:func:`load_chunk` assembles a whole builder chunk's ``(X, y, metadata)``
entries at once:

1. **Fetch dedup** — machines are partitioned by
   :func:`~gordo_tpu.ingest.fingerprint.dataset_fingerprint`; each
   distinct fingerprint fetches and assembles ONCE, duplicates copy the
   leader's stacked slot (one float32 memcpy) and deep-copy its
   metadata.
2. **Columnar assembly** — fingerprints whose fetched series share one
   index geometry (equal timestamps, same resolution) resample and join
   as ONE ``np.add.reduceat`` pass over a ``(rows, Σtags)`` float64
   matrix — the per-machine fast path of
   :meth:`TimeSeriesDataset._resample_one_arrays` extended across the
   machine axis, using the same :func:`resample_prep` geometry so the
   two cannot drift.
3. **Stacked handoff** — results land directly in a preallocated
   ``(m_pad, n, tags)`` float32 buffer (capacity from the dispatch
   plane's model-axis padding); per-machine ``X``/``y`` are views of it,
   and ``FleetDiffBuilder`` adopts the buffer without re-stacking
   (``_stack_machine_axis`` / in-place model padding in
   ``gordo_tpu/parallel/anomaly.py``).

Anything the columnar pass cannot express — row filters, non-mean
aggregation, targets != inputs, ragged per-tag indexes, subclassed
assembly — takes :func:`_load_fallback`, the sanctioned per-machine
``dataset.get_data()`` path.  Both paths produce byte-identical arrays
and metadata (pinned by tests/test_ingest.py and the ``bench --stage
build_ingest`` in-bench attestation).  ``GORDO_INGEST=off`` is the kill
switch.

scripts/lint.py bans per-machine pandas verbs (``.resample(...)``,
``pd.concat``, ``pd.DataFrame``) in this module outside the sanctioned
fallback — the hot path must stay columnar numpy.
"""

from __future__ import annotations

import copy
import logging
import os
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from gordo_tpu import telemetry
from gordo_tpu.dataset.base import GordoBaseDataset
from gordo_tpu.dataset.datasets import (
    InsufficientDataError,
    TimeSeriesDataset,
    resample_prep,
    summary_statistics_arrays,
)
from gordo_tpu.ingest.fingerprint import dataset_fingerprint

logger = logging.getLogger(__name__)

#: kill switch: GORDO_INGEST=off routes every machine through the
#: per-machine fallback (docs/configuration.md)
ENV_INGEST = "GORDO_INGEST"

# -- telemetry instruments (docs/observability.md) --------------------------
_FETCH_TOTAL = telemetry.counter(
    "gordo_ingest_fetch_total",
    "Provider fetches by the fleet ingest plane, by outcome "
    "(fetched: one provider pull; deduped: shared a fingerprint-equal "
    "machine's fetch)",
    labels=("path",),
)
DEDUP_HITS_TOTAL = telemetry.counter(
    "gordo_build_ingest_dedup_hits_total",
    "Machines whose dataset fetch was satisfied by another machine with "
    "an identical dataset fingerprint (one fetch per distinct "
    "fingerprint — see gordo_tpu/ingest/fingerprint.py)",
)
_MACHINES_TOTAL = telemetry.counter(
    "gordo_ingest_machines_total",
    "Machines assembled by the fleet ingest plane, by path "
    "(vectorized: columnar cross-machine pass; fallback: sanctioned "
    "per-machine get_data; deduped: slot-copied from a fingerprint twin)",
    labels=("path",),
)
_STAGE_SECONDS = telemetry.histogram(
    "gordo_ingest_stage_seconds",
    "Busy seconds per ingest-plane stage (fetch: one fingerprint's "
    "provider pull; resample: one geometry group's columnar pass; "
    "assemble: stacked-buffer fill; finalize: stats + metadata; "
    "fallback: one per-machine get_data)",
    labels=("stage",),
)


def resolve_enabled(flag: Optional[bool] = None) -> bool:
    """Ingest-plane gate: an explicit argument beats ``GORDO_INGEST``
    (default on)."""
    if flag is not None:
        return bool(flag)
    return os.environ.get(ENV_INGEST, "on").strip().lower() not in (
        "off", "0", "false", "no",
    )


# -- stacked-buffer ownership ----------------------------------------------
# The dispatch plane may adopt (and pad in place) ONLY buffers this plane
# allocated — a registry of live base arrays makes the mutation provably
# sanctioned instead of inferred from view geometry alone.
_STACK_BASES: Dict[int, Any] = {}


def _register_stack(base: np.ndarray, live_slots: int = 0) -> None:
    key = id(base)
    ref = weakref.ref(
        base, lambda _ref, _key=key: _STACK_BASES.pop(_key, None)
    )
    _STACK_BASES[key] = [ref, int(live_slots)]


def _set_live_slots(base: np.ndarray, live_slots: int) -> None:
    entry = _STACK_BASES.get(id(base))
    if entry is not None:
        entry[1] = int(live_slots)


def owned_stack_base(arr: np.ndarray) -> Optional[np.ndarray]:
    """The ingest-owned stacked buffer ``arr`` is a view of, or None."""
    base = getattr(arr, "base", None)
    if base is None:
        return None
    entry = _STACK_BASES.get(id(base))
    if entry is None or entry[0]() is not base:
        return None
    return base


def stack_live_slots(base: np.ndarray) -> int:
    """Machine slots of an ingest-owned buffer holding real data; rows at
    and past this index are scratch the dispatch plane may fill with
    model-axis padding in place."""
    entry = _STACK_BASES.get(id(base))
    return entry[1] if entry is not None else 0


# -- the sanctioned per-machine fallback ------------------------------------

def _load_fallback(dataset, align_lengths: Optional[int]):
    """Per-machine ``get_data()`` — the same work the pre-ingest builder
    did per machine, kept as the escape hatch for everything the
    columnar pass cannot express (byte-identical output either way)."""
    t0 = time.time()
    X, y = dataset.get_data()
    X = np.asarray(X, np.float32)
    y = np.asarray(y, np.float32)
    if align_lengths and len(X) >= align_lengths:
        keep = (len(X) // align_lengths) * align_lengths
        # newest rows win (mirrors the builder's truncation)
        X, y = X[len(X) - keep:], y[len(y) - keep:]
    dt = time.time() - t0
    _STAGE_SECONDS.observe(dt, "fallback")
    _MACHINES_TOTAL.inc(1.0, "fallback")
    return (X, y, dataset.get_metadata(), dt)


def _vectorizable(dataset) -> bool:
    """Whether the columnar cross-machine pass can express this dataset
    exactly: stock TimeSeriesDataset assembly (subclasses overriding it
    fall back), mean aggregation, no row filter, targets == inputs,
    unique tag names."""
    if not isinstance(dataset, TimeSeriesDataset):
        return False
    cls = type(dataset)
    if (
        cls.get_data is not TimeSeriesDataset.get_data
        or cls._join_timeseries is not TimeSeriesDataset._join_timeseries
        or cls._resample_one_arrays
        is not TimeSeriesDataset._resample_one_arrays
    ):
        return False
    if dataset.aggregation_methods != "mean" or dataset.row_filter:
        return False
    if dataset.target_tag_list != dataset.tag_list:
        return False
    names = [t.name for t in dataset.tag_list]
    return bool(names) and len(set(names)) == len(names)


# -- vectorized assembly ----------------------------------------------------

class _FpGroup:
    """One distinct dataset fingerprint: the leader dataset, every machine
    name sharing it, and (once fetched) the shared raw arrays."""

    __slots__ = (
        "fp", "dataset", "names", "index", "idx_ns", "values", "nanos",
        "col0", "keep", "n_rows", "offset", "meta", "error", "slots",
    )

    def __init__(self, fp: str, dataset) -> None:
        self.fp = fp
        self.dataset = dataset
        self.names: List[str] = []
        self.index = None          # shared pd.DatetimeIndex
        self.idx_ns = None         # its int64 ns view
        self.values = None         # (n_raw, T) float64
        self.nanos = 0
        self.col0 = 0              # column offset in the geometry matrix
        self.keep = None           # joined-row mask on the bin grid
        self.n_rows = 0            # rows after join (== after filter)
        self.offset = 0            # head rows dropped by align_lengths
        self.meta: Optional[Dict[str, Any]] = None
        self.error: Optional[Exception] = None
        self.slots: List[Tuple[str, int]] = []  # (machine name, slot)


def _fetch_group(g: _FpGroup) -> bool:
    """Provider fetch for one fingerprint: array-grain when the provider
    supports it, else per-tag series flattened to one matrix.  Returns
    False (no exception) when the fetched shape disqualifies the
    vectorized path — the caller reroutes the group to the fallback."""
    ds = g.dataset
    t0 = time.time()
    tags = ds.tag_list  # targets == inputs (checked by _vectorizable)
    fetched = ds.data_provider.load_arrays(
        ds.train_start_date, ds.train_end_date, tags
    )
    if fetched is None:
        series_list = list(
            ds.data_provider.load_series(
                ds.train_start_date, ds.train_end_date, tags
            )
        )
        if len(series_list) != len(tags) or not all(
            len(s) and (
                s.index is series_list[0].index
                or s.index.equals(series_list[0].index)
            )
            for s in series_list
        ):
            return False
        index = series_list[0].index
        values = np.column_stack(
            [s.to_numpy(dtype=np.float64, copy=False) for s in series_list]
        )
    else:
        index, values = fetched
    _FETCH_TOTAL.inc(1.0, "fetched")
    _STAGE_SECONDS.observe(time.time() - t0, "fetch")
    if (
        len(index) == 0
        or str(index.tz) != "UTC"
        or not index.is_monotonic_increasing
    ):
        return False
    try:
        g.nanos = pd.tseries.frequencies.to_offset(ds.resolution).nanos
    except ValueError:  # non-fixed frequency — pandas path territory
        return False
    g.index = index
    g.idx_ns = index.asi8 if index.unit == "ns" else index.as_unit("ns").asi8
    g.values = values
    return True


def _assemble_geometry_group(
    groups: List[_FpGroup],
    prep: Tuple[np.ndarray, int, np.ndarray, pd.DatetimeIndex],
    align_lengths: Optional[int],
    capacity: Optional[Callable[[int], int]],
    out: Dict[str, Any],
) -> None:
    """One shared-index geometry group end to end: columnar resample,
    per-fingerprint join mask + threshold, stacked-buffer fill, stats and
    metadata — no per-machine pandas anywhere."""
    starts, grid_size, scatter, _label = prep
    t0 = time.time()
    if len(groups) == 1:
        V = groups[0].values
    else:
        V = np.concatenate([g.values for g in groups], axis=1)
    col = 0
    for g in groups:
        g.col0 = col
        col += g.values.shape[1]
    # the machine-axis extension of _resample_one_arrays: one reduceat
    # over every tag of every machine in the group (bit-identical per
    # column — reduction order along axis 0 is the per-tag order)
    nan_mask = np.isnan(V)
    had_nan = bool(nan_mask.any())
    if had_nan:
        sums = np.add.reduceat(np.where(nan_mask, 0.0, V), starts, axis=0)
        valid = np.add.reduceat((~nan_mask).astype(np.int64), starts, axis=0)
        means = np.divide(
            sums, valid, out=np.full(sums.shape, np.nan), where=valid > 0
        )
    else:
        # NaN-free input: the where-copy and the int64 count pass drop
        # out; sums/counts divides the identical float64 operands, so
        # the quotient bits match the masked-divide branch exactly
        sums = np.add.reduceat(V, starts, axis=0)
        counts = np.diff(np.append(starts, V.shape[0]))
        means = sums / counts[:, None]
    if len(starts) == grid_size:
        # occupied bins are strictly increasing, so covering every bin
        # means scatter is the identity — the grid IS the means matrix
        grid = means
        clean = not had_nan
    else:
        grid = np.full((grid_size, col), np.nan)
        grid[scatter] = means
        clean = False
    _STAGE_SECONDS.observe(time.time() - t0, "resample")

    # join mask + n_samples_threshold per fingerprint.  A clean group
    # (NaN-free input, every bin occupied) has no NaN anywhere in the
    # grid: every fingerprint keeps every row, no per-fp isnan scans.
    alive: List[_FpGroup] = []
    for g in groups:
        if clean:
            g.keep = None
            g.n_rows = grid_size
        else:
            sub = grid[:, g.col0 : g.col0 + g.values.shape[1]]
            g.keep = ~np.isnan(sub).any(axis=1)
            g.n_rows = int(g.keep.sum())
        ds = g.dataset
        if g.n_rows < max(ds.n_samples_threshold, 1):
            g.error = InsufficientDataError(
                f"Only {g.n_rows} rows after filtering "
                f"(threshold {ds.n_samples_threshold}) for period "
                f"{ds.train_start_date} → {ds.train_end_date}"
            )
            for name in g.names:
                out[name] = g.error
            continue
        g.offset = 0
        if align_lengths and g.n_rows >= align_lengths:
            g.offset = g.n_rows - (g.n_rows // align_lengths) * align_lengths
        alive.append(g)

    # clean group: every fingerprint's stats matrix is a column slice of
    # the one grid — four whole-grid reductions replace 4 x len(groups)
    # per-fingerprint ones (numpy's axis-0 reduction accumulates row by
    # row, so each column's result is bit-identical either way)
    grid_stats = None
    if clean and len(alive) > 1:
        t0 = time.time()
        with np.errstate(all="ignore"):
            import warnings

            with warnings.catch_warnings():
                warnings.simplefilter("ignore", category=RuntimeWarning)
                grid_stats = (
                    np.nanmean(grid, axis=0),
                    np.nanstd(grid, axis=0, ddof=1),
                    np.nanmin(grid, axis=0),
                    np.nanmax(grid, axis=0),
                )
        _STAGE_SECONDS.observe(time.time() - t0, "finalize")

    # stacked buffers: one per (final row count, tag count) subgroup;
    # every machine (dups included) gets its own slot so the dispatch
    # plane sees consecutive leading-axis views of one base
    t0 = time.time()
    by_shape: Dict[Tuple[int, int], List[_FpGroup]] = {}
    for g in alive:
        shape = (g.n_rows - g.offset, g.values.shape[1])
        by_shape.setdefault(shape, []).append(g)
    for (n_final, n_tags), members in by_shape.items():
        m_total = sum(len(g.names) for g in members)
        cap = max(capacity(m_total) if capacity else m_total, m_total)
        base = np.empty((cap, n_final, n_tags), dtype=np.float32)
        _register_stack(base)
        slot = 0
        for g in members:
            sub = grid[:, g.col0 : g.col0 + g.values.shape[1]]
            d64 = sub if g.n_rows == grid_size else sub[g.keep]
            base[slot] = d64[g.offset:] if g.offset else d64
            g.slots = [(g.names[0], slot)]
            lead = slot
            slot += 1
            for dup in g.names[1:]:
                base[slot] = base[lead]  # fingerprint twin: one memcpy
                g.slots.append((dup, slot))
                slot += 1
            # stats/metadata read the pre-truncation float64 rows, exactly
            # like the per-machine path (align truncation happens in the
            # builder AFTER get_data there)
            stats_dict = None
            if grid_stats is not None:
                smean, sstd, smin, smax = grid_stats
                stats_dict = {
                    t.name: {
                        "mean": float(smean[g.col0 + k]),
                        "std": float(sstd[g.col0 + k]),
                        "min": float(smin[g.col0 + k]),
                        "max": float(smax[g.col0 + k]),
                    }
                    for k, t in enumerate(g.dataset.tag_list)
                }
            g.meta = _group_metadata(g, d64, grid_size, stats_dict)
            for i, (name, s) in enumerate(g.slots):
                X = base[s]
                meta = g.meta if i == 0 else copy.deepcopy(g.meta)
                out[name] = (X, X, meta, 0.0)
                _MACHINES_TOTAL.inc(1.0, "vectorized" if i == 0 else "deduped")
        _set_live_slots(base, slot)
    _STAGE_SECONDS.observe(time.time() - t0, "assemble")


def _group_metadata(
    g: _FpGroup,
    d64: np.ndarray,
    grid_size: int,
    stats: Optional[Dict[str, Dict[str, float]]] = None,
) -> Dict[str, Any]:
    """The exact metadata dict ``get_data`` + ``get_metadata`` would
    record for this fingerprint (same keys, same insertion order — the
    metadata JSON is a byte-parity artifact)."""
    t0 = time.time()
    ds = g.dataset
    n_raw = len(g.index)
    names = [t.name for t in ds.tag_list]
    meta: Dict[str, Any] = {
        "tag_loading_metadata": {
            name: {
                "original_length": int(n_raw),
                "resampled_length": int(grid_size),
            }
            for name in names
        },
        "train_start_date": str(ds.train_start_date),
        "train_end_date": str(ds.train_end_date),
        "resolution": ds.resolution,
        "row_filter": ds.row_filter,
        "rows_after_join": int(g.n_rows),
        "rows_after_filter": int(g.n_rows),
        "filtered_periods": 0,
        "tag_list": [t.to_json() for t in ds.tag_list],
        "target_tag_list": [t.to_json() for t in ds.target_tag_list],
        "data_provider": ds.data_provider.to_dict(),
        "summary_statistics": (
            stats
            if stats is not None
            else summary_statistics_arrays(d64, names)
        ),
    }
    _STAGE_SECONDS.observe(time.time() - t0, "finalize")
    return meta


def load_chunk(
    machines: Sequence[Any],
    align_lengths: Optional[int] = None,
    capacity: Optional[Callable[[int], int]] = None,
    stats: Optional[Dict[str, int]] = None,
) -> Dict[str, Any]:
    """Assemble one builder chunk: ``{machine name: (X, y, metadata,
    load_seconds) | Exception}``.

    ``machines`` are Machine-likes (``.name``, ``.dataset`` config
    mapping).  ``capacity(m)`` maps a stacked subgroup's machine count to
    its buffer capacity (the builder passes the dispatch plane's
    model-axis padding so the buffer IS the ``(m_pad, n, tags)`` array
    the fleet program stages).  ``stats`` (optional dict) accumulates
    ``machines / vectorized / deduped / fallback / fetches`` counts for
    build-result reporting.  Failures are per-machine values, never
    raises — exactly like the per-machine loader pool it replaces."""
    t_chunk = time.time()
    out: Dict[str, Any] = {}
    by_fp: Dict[str, _FpGroup] = {}
    order: List[_FpGroup] = []
    fallback: List[Tuple[str, Any]] = []  # (name, dataset)

    for m in machines:
        cfg = dict(m.dataset)
        try:
            fp = dataset_fingerprint(cfg)
            g = by_fp.get(fp)
            if g is not None:
                g.names.append(m.name)
                DEDUP_HITS_TOTAL.inc(1.0)
                _FETCH_TOTAL.inc(1.0, "deduped")
                continue
            dataset = GordoBaseDataset.from_dict(cfg)
        except Exception as exc:
            out[m.name] = exc
            continue
        g = _FpGroup(fp, dataset)
        g.names.append(m.name)
        by_fp[fp] = g
        order.append(g)

    # fetch vectorizable fingerprints; everything else → fallback
    geometry: Dict[Tuple, List[_FpGroup]] = {}
    for g in order:
        ok = False
        if _vectorizable(g.dataset):
            try:
                ok = _fetch_group(g)
            except Exception as exc:
                g.error = exc
                for name in g.names:
                    out[name] = exc
                continue
        if not ok:
            fallback.append((g.names[0], g.dataset))
            for dup in g.names[1:]:
                fallback.append((dup, None))  # share the leader's entry
            continue
        key = (
            len(g.idx_ns), int(g.idx_ns[0]), int(g.idx_ns[-1]), g.nanos,
            g.index.name,
        )
        # content-verified grouping: equal endpoints but different interior
        # timestamps must not share binning geometry
        bucket = geometry.setdefault(key, [])
        while bucket and not np.array_equal(bucket[0].idx_ns, g.idx_ns):
            key = key + ("'",)
            bucket = geometry.setdefault(key, [])
        bucket.append(g)

    for groups in geometry.values():
        ref = groups[0]
        prep = resample_prep(ref.index, ref.nanos)
        try:
            _assemble_geometry_group(
                groups, prep, align_lengths, capacity, out
            )
        except Exception:
            logger.exception(
                "vectorized ingest failed for %d fingerprint group(s); "
                "falling back per machine", len(groups),
            )
            for g in groups:
                if g.names and g.names[0] not in out:
                    fallback.append((g.names[0], g.dataset))
                    for dup in g.names[1:]:
                        fallback.append((dup, None))

    # the sanctioned per-machine path (+ fingerprint-shared entries)
    shared: Dict[str, str] = {}  # dup name -> leader name (fallback dups)
    last_leader: Optional[str] = None
    for name, dataset in fallback:
        if dataset is None:
            shared[name] = last_leader
            continue
        last_leader = name
        try:
            out[name] = _load_fallback(dataset, align_lengths)
        except Exception as exc:
            out[name] = exc
    for dup, leader in shared.items():
        src = out.get(leader)
        if src is None or isinstance(src, Exception):
            out[dup] = src if src is not None else RuntimeError(
                f"fingerprint leader {leader} produced no entry"
            )
        else:
            X, y, meta, _secs = src
            out[dup] = (X, y, copy.deepcopy(meta), 0.0)
            _MACHINES_TOTAL.inc(1.0, "deduped")

    # attribute load seconds evenly across the chunk's successful entries
    # (wall-clock only — data_query_duration_sec is volatile metadata)
    dt = time.time() - t_chunk
    good = [n for n, e in out.items() if not isinstance(e, Exception)]
    share = dt / max(len(good), 1)
    for n in good:
        X, y, meta, secs = out[n]
        out[n] = (X, y, meta, secs or share)

    if stats is not None:
        n_dups = sum(len(g.names) - 1 for g in order)
        stats["machines"] = stats.get("machines", 0) + len(list(machines))
        stats["dedup_hits"] = stats.get("dedup_hits", 0) + n_dups
        stats["fetches"] = stats.get("fetches", 0) + len(order)
        n_fallback = len([1 for _n, d in fallback if d is not None])
        stats["fallback"] = stats.get("fallback", 0) + n_fallback
        stats["vectorized"] = (
            stats.get("vectorized", 0)
            + sum(1 for g in order if g.slots and g.error is None)
        )
    return out
