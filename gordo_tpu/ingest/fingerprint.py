"""Dataset fingerprints: the shared definition of "same data".

Two grains, both JSON-canonical (sorted keys, name-normalized tags) so
equal fingerprints mean equal strings across processes and releases:

- :func:`provider_fingerprint` — the FETCH grain the r18 backfill runner
  introduced: frames are shareable iff tags + resolution + provider
  match.  The batch plane keys its one-fetch-per-fingerprint cache on
  this (the scoring window is fixed per backfill run, so it lives
  outside the key).
- :func:`dataset_fingerprint` — the full OUTPUT grain the build-ingest
  plane dedups on: everything that shapes ``get_data()``'s result —
  window, tags, targets, resolution, filter, aggregation, thresholds,
  provider.  Machines with equal fingerprints get byte-identical frames,
  so the builder fetches and assembles once and copies slots; any
  differing field changes the JSON and misses the cache (wrong dedup
  would train machines on the wrong data — tests/test_ingest.py pins
  both directions).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List


def _tag_names(tags: Any) -> List[str]:
    """Tag names from any config/metadata spelling (str | dict | SensorTag)."""
    out = []
    for t in tags or []:
        if isinstance(t, dict):
            out.append(str(t.get("name")))
        else:
            out.append(str(getattr(t, "name", t)))
    return out


def provider_fingerprint(dataset_meta: Dict[str, Any]) -> str:
    """Fetch-grain fingerprint over dataset METADATA or config: frames are
    shareable iff tags + resolution + provider match — replicated fleets
    collapse to one provider fetch (hoisted from the r18 backfill
    runner's ``_dataset_fingerprint``; same JSON shape)."""
    return json.dumps(
        {
            "tags": _tag_names(
                dataset_meta.get("tag_list") or dataset_meta.get("tags")
            ),
            "resolution": dataset_meta.get("resolution", "10min"),
            "provider": dataset_meta.get("data_provider"),
        },
        sort_keys=True,
        default=str,
    )


def dataset_fingerprint(dataset_cfg: Dict[str, Any]) -> str:
    """Output-grain fingerprint over a machine's dataset CONFIG: covers
    every field that shapes ``get_data()``'s frames.  Conservative by
    construction — unknown keys are hashed in verbatim, so a config the
    fingerprint does not understand can only MISS the dedup cache, never
    falsely hit it."""
    tags = _tag_names(dataset_cfg.get("tag_list") or dataset_cfg.get("tags"))
    targets = dataset_cfg.get("target_tag_list")
    doc = {
        "type": dataset_cfg.get("type"),
        "window": [
            str(dataset_cfg.get("train_start_date")),
            str(dataset_cfg.get("train_end_date")),
        ],
        "tags": tags,
        "targets": _tag_names(targets) if targets else tags,
        "resolution": dataset_cfg.get("resolution", "10min"),
        "row_filter": dataset_cfg.get("row_filter"),
        "row_filter_buffer_size": dataset_cfg.get("row_filter_buffer_size", 0),
        "aggregation_methods": dataset_cfg.get("aggregation_methods", "mean"),
        "n_samples_threshold": dataset_cfg.get("n_samples_threshold", 0),
        "asset": dataset_cfg.get("asset"),
        "provider": dataset_cfg.get("data_provider"),
        "extra": {
            k: v
            for k, v in dataset_cfg.items()
            if k
            not in (
                "type",
                "train_start_date",
                "train_end_date",
                "tag_list",
                "tags",
                "target_tag_list",
                "resolution",
                "row_filter",
                "row_filter_buffer_size",
                "aggregation_methods",
                "n_samples_threshold",
                "asset",
                "data_provider",
            )
        },
    }
    return json.dumps(doc, sort_keys=True, default=str)
