"""Coordinator/worker bring-up around ``jax.distributed.initialize``.

One :class:`DistributedRuntime` per worker process.  Process 0 hosts the
coordination service; every process connects to it, after which
``jax.devices()`` is the GLOBAL device list and the canonical
``("models", "data")`` mesh spans hosts (``global_mesh``).  Barriers ride
the coordination service's own ``wait_at_barrier`` — a real distributed
barrier with a timeout, which is also the worker-death detector: a killed
peer stops heartbeating, every surviving process's barrier raises
:class:`BarrierTimeout` (the coordination service names the dead task in
the error), and the caller exits with the resumable per-shard code
instead of hanging the slice.

Configuration comes from either the CLI spec ``coordinator:port,N,pid``
(:func:`parse_multihost_spec`) or the env equivalents
``GORDO_COORDINATOR`` / ``GORDO_NUM_PROCESSES`` / ``GORDO_PROCESS_ID``
(:meth:`DistributedConfig.from_env`) — the latter is what the generated
Indexed-Job manifest wires up (``workflow/generator.py``).

Hazard notes (both reproduced in-container, see scripts/multihost_dryrun.py):

- ``jax.distributed.shutdown()`` SIGABRTs when a peer already died; the
  resumable exit path must therefore use ``os._exit`` and NEVER attempt
  the clean shutdown (:meth:`DistributedRuntime.shutdown` guards this).
- On simulated CPU hosts the per-process virtual device count must be in
  ``XLA_FLAGS`` BEFORE jax initializes a backend, so ``ensure_env`` runs
  first and raises if the backend already exists with the wrong count.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass
from typing import Optional

from gordo_tpu import faults, telemetry

logger = logging.getLogger(__name__)

# -- telemetry instruments (docs/observability.md) --------------------------
_BARRIER_WAIT_SECONDS = telemetry.histogram(
    "gordo_barrier_wait_seconds",
    "Time this process spent waiting at cross-process barriers, by name",
    labels=("barrier",),
)
_BARRIER_TIMEOUTS_TOTAL = telemetry.counter(
    "gordo_barrier_timeouts_total",
    "Barriers that expired (a peer is dead or wedged), by name",
    labels=("barrier",),
)

#: default barrier timeout: generous enough for a straggler host's XLA
#: compile skew, far below a wedged-slice babysitting interval
DEFAULT_BARRIER_TIMEOUT_SECONDS = 600.0

ENV_COORDINATOR = "GORDO_COORDINATOR"
ENV_NUM_PROCESSES = "GORDO_NUM_PROCESSES"
ENV_PROCESS_ID = "GORDO_PROCESS_ID"
ENV_LOCAL_DEVICES = "GORDO_LOCAL_DEVICES"
ENV_BARRIER_TIMEOUT = "GORDO_BARRIER_TIMEOUT"


class BarrierTimeout(RuntimeError):
    """A cross-process barrier expired — some peer is dead or wedged."""


@dataclass
class DistributedConfig:
    """One process's view of the multi-host job."""

    coordinator: str  #: ``host:port`` of process 0's coordination service
    num_processes: int
    process_id: int
    #: simulated hosts only: virtual CPU devices per process (sets
    #: ``--xla_force_host_platform_device_count``); None on real TPU hosts
    local_device_count: Optional[int] = None
    barrier_timeout: float = DEFAULT_BARRIER_TIMEOUT_SECONDS

    def __post_init__(self):
        if ":" not in self.coordinator:
            raise ValueError(
                f"coordinator must be host:port, got {self.coordinator!r}"
            )
        if self.num_processes < 1:
            raise ValueError(f"num_processes must be >= 1, got {self.num_processes}")
        if not 0 <= self.process_id < self.num_processes:
            raise ValueError(
                f"process_id {self.process_id} outside [0, {self.num_processes})"
            )

    @classmethod
    def from_env(cls, environ=None) -> Optional["DistributedConfig"]:
        """Build from ``GORDO_*`` env vars; None when not a multi-host job
        (no ``GORDO_COORDINATOR``)."""
        env = os.environ if environ is None else environ
        coordinator = env.get(ENV_COORDINATOR)
        if not coordinator:
            return None
        missing = [
            name for name in (ENV_NUM_PROCESSES, ENV_PROCESS_ID)
            if not env.get(name)
        ]
        if missing:
            raise ValueError(
                f"{ENV_COORDINATOR} is set but {missing} are not — a "
                "multi-host worker needs all three"
            )
        local = env.get(ENV_LOCAL_DEVICES)
        timeout = env.get(ENV_BARRIER_TIMEOUT)
        return cls(
            coordinator=coordinator,
            num_processes=int(env[ENV_NUM_PROCESSES]),
            process_id=int(env[ENV_PROCESS_ID]),
            local_device_count=int(local) if local else None,
            barrier_timeout=(
                float(timeout) if timeout else DEFAULT_BARRIER_TIMEOUT_SECONDS
            ),
        )


def parse_multihost_spec(spec: str) -> DistributedConfig:
    """Parse the CLI form ``coordinator:port,N,pid``.

    Example: ``--multihost 10.0.0.2:8476,16,3`` — 16 processes, this one
    is process 3, process 0 serves the coordination service on port 8476.
    """
    parts = [p.strip() for p in spec.split(",")]
    if len(parts) != 3:
        raise ValueError(
            f"multihost spec must be 'coordinator:port,N,pid', got {spec!r}"
        )
    try:
        n, pid = int(parts[1]), int(parts[2])
    except ValueError as exc:
        raise ValueError(
            f"multihost spec N and pid must be integers, got {spec!r}"
        ) from exc
    return DistributedConfig(coordinator=parts[0], num_processes=n, process_id=pid)


class DistributedRuntime:
    """Lifecycle owner for one worker process of a multi-host job.

    Usage::

        runtime = DistributedRuntime(config)
        runtime.ensure_env()     # BEFORE any jax import touches a backend
        runtime.initialize()     # jax.distributed + device checks
        mesh = runtime.global_mesh()           # "models" axis spans hosts
        runtime.barrier("pre-build")
        ...                       # build this process's shard
        runtime.barrier("post-build")          # raises BarrierTimeout on
        runtime.shutdown()                     # peer death -> resumable exit
    """

    def __init__(self, config: DistributedConfig):
        self.config = config
        self.initialized = False
        self._barrier_failed = False

    # -- environment ---------------------------------------------------------
    def ensure_env(self) -> None:
        """Pin the simulated-host env BEFORE jax backend init.

        No-op on real hosts (``local_device_count`` unset).  On simulated
        hosts, sets ``--xla_force_host_platform_device_count`` so each
        forked process contributes that many virtual CPU devices to the
        global mesh — and raises if a backend already initialized with a
        different count (the flag is dead after backend init)."""
        n = self.config.local_device_count
        if n is None:
            return
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()
        import jax._src.xla_bridge as xb

        # same guard as tests/conftest.py: backend discovery must never
        # touch the axon tunnel plugin from a forked worker
        xb._backend_factories.pop("axon", None)
        if xb._backends:  # backend already up: the flag can no longer act
            import jax

            have = len(jax.local_devices())
            if have != n:
                raise RuntimeError(
                    f"jax backend initialized with {have} local devices "
                    f"before ensure_env could request {n}; set XLA_FLAGS="
                    f"--xla_force_host_platform_device_count={n} in the "
                    "worker's environment instead"
                )

    # -- bring-up ------------------------------------------------------------
    def initialize(self) -> None:
        """``jax.distributed.initialize`` + post-init sanity checks."""
        self.ensure_env()
        import jax

        cfg = self.config
        if cfg.local_device_count is not None or (
            os.environ.get("JAX_PLATFORMS", "").startswith("cpu")
        ):
            # simulated hosts: XLA:CPU refuses multi-process computations
            # unless the gloo CPU-collectives backend is selected (must
            # happen before backend init; reproduced in-container)
            try:
                jax.config.update(
                    "jax_cpu_collectives_implementation", "gloo"
                )
            except Exception:  # jax without the option: surfaced at jit time
                logger.warning(
                    "could not enable gloo CPU collectives; cross-process "
                    "CPU programs may be refused by XLA"
                )
        logger.info(
            "multihost init: process %d/%d, coordinator %s",
            cfg.process_id, cfg.num_processes, cfg.coordinator,
        )
        jax.distributed.initialize(
            coordinator_address=cfg.coordinator,
            num_processes=cfg.num_processes,
            process_id=cfg.process_id,
        )
        if jax.process_count() != cfg.num_processes:
            raise RuntimeError(
                f"jax sees {jax.process_count()} processes, config says "
                f"{cfg.num_processes}"
            )
        if jax.process_index() != cfg.process_id:
            raise RuntimeError(
                f"jax assigned process_index {jax.process_index()}, config "
                f"says {cfg.process_id}"
            )
        self.initialized = True
        logger.info(
            "multihost init ok: %d global devices (%d local) across %d "
            "processes",
            len(jax.devices()), len(jax.local_devices()), jax.process_count(),
        )

    # -- meshes --------------------------------------------------------------
    def global_mesh(self, data_parallel: int = 1):
        """The canonical mesh over ALL processes' devices (``"models"``
        axis spans hosts)."""
        from gordo_tpu.mesh import global_fleet_mesh

        return global_fleet_mesh(data_parallel=data_parallel)

    def local_mesh(self, data_parallel: int = 1):
        """Mesh over THIS process's devices only — what the per-shard
        fleet build runs on (each process trains its own machine shard;
        the global mesh carries bring-up validation and any future
        cross-host program).  None on a single local device, matching the
        single-host CLI's behaviour."""
        import jax

        from gordo_tpu.mesh import fleet_mesh

        local = jax.local_devices()
        if len(local) <= 1:
            return None
        return fleet_mesh(local, data_parallel=data_parallel)

    def validate_global_mesh(self) -> int:
        """Run one tiny sharded program over the process-spanning mesh and
        check every process's devices actually participated.  Returns the
        global device count.  This is the 'real cross-process init'
        evidence the dryrun asserts on — initialize() succeeding only
        proves the coordination handshake, not that XLA can place a
        program across the process boundary."""
        import jax
        import numpy as np

        from gordo_tpu.mesh import model_sharding

        mesh = self.global_mesh()  # data axis = 1: models axis is every device
        flat = list(mesh.devices.reshape(-1))
        n = len(flat)
        full = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
        # this process's rows, derived from mesh positions (never device ids)
        mine = [
            i for i, d in enumerate(flat)
            if d.process_index == jax.process_index()
        ]
        sharding = model_sharding(mesh)
        x = jax.make_array_from_process_local_data(
            sharding, full[mine], full.shape
        )
        from gordo_tpu import compile as compile_plane

        y = compile_plane.jit(
            lambda a: a * 2.0, name="runtime.mesh_check",
            out_shardings=sharding,
        )(x)
        # every process checks ITS addressable shards came back right
        for shard in y.addressable_shards:
            np.testing.assert_array_equal(
                np.asarray(shard.data), full[shard.index] * 2.0
            )
        return n

    # -- coordination --------------------------------------------------------
    def barrier(self, name: str, timeout: Optional[float] = None) -> None:
        """Block until every process reaches ``barrier(name)``.

        Rides the coordination service (no device collectives — works
        mid-build regardless of what the devices are doing).  Raises
        :class:`BarrierTimeout` after ``timeout`` seconds; a dead peer is
        the usual cause and the service names it in the message."""
        if not self.initialized:
            raise RuntimeError("barrier() before initialize()")
        timeout = self.config.barrier_timeout if timeout is None else timeout
        from jax._src import distributed as jax_distributed

        client = jax_distributed.global_state.client
        t0 = time.monotonic()
        if faults.enabled():
            # chaos seam: an injected peer loss behaves exactly like the
            # real thing — the barrier "expires", the timeout is counted,
            # and the caller takes the resumable-exit path
            try:
                faults.check(
                    "barrier.wait", barrier=name,
                    process_id=self.config.process_id,
                )
            except faults.InjectedFault as exc:
                self._note_barrier_timeout(name, timeout, t0)
                raise BarrierTimeout(
                    f"barrier {name!r}: injected peer loss "
                    f"(process {self.config.process_id}/"
                    f"{self.config.num_processes}): {exc}"
                ) from exc
        try:
            if client is not None and hasattr(client, "wait_at_barrier"):
                client.wait_at_barrier(
                    f"gordo:{name}", timeout_in_ms=int(timeout * 1000)
                )
            else:  # pragma: no cover - jax without the coordination client
                self._sync_with_thread_timeout(name, timeout)
        except BarrierTimeout:
            self._note_barrier_timeout(name, timeout, t0)
            raise
        except Exception as exc:
            self._note_barrier_timeout(name, timeout, t0)
            raise BarrierTimeout(
                f"barrier {name!r} failed after <= {timeout:.0f}s "
                f"(process {self.config.process_id}/"
                f"{self.config.num_processes}): {exc}"
            ) from exc
        _BARRIER_WAIT_SECONDS.observe(time.monotonic() - t0, name)

    def _note_barrier_timeout(
        self, name: str, timeout: float, t0: float
    ) -> None:
        """Count + one structured line per expired barrier (previously the
        only trace was the raised exception's message)."""
        self._barrier_failed = True
        _BARRIER_WAIT_SECONDS.observe(time.monotonic() - t0, name)
        _BARRIER_TIMEOUTS_TOTAL.inc(1.0, name)
        telemetry.log_event(
            logger, "barrier_timeout",
            barrier=name,
            timeout_s=round(timeout, 1),
            process_id=self.config.process_id,
            num_processes=self.config.num_processes,
        )

    @staticmethod
    def _sync_with_thread_timeout(name: str, timeout: float) -> None:
        """Fallback barrier: ``sync_global_devices`` on a watchdog thread.
        The sync has no native timeout, so a join-timeout abandons the
        (daemon) thread and raises — the abandoned thread blocks forever,
        which is fine because the caller is about to ``os._exit``."""
        from jax.experimental import multihost_utils

        done = threading.Event()
        error: list = []

        def _run():
            try:
                multihost_utils.sync_global_devices(f"gordo:{name}")
            except Exception as exc:  # surfaced below
                error.append(exc)
            finally:
                done.set()

        t = threading.Thread(target=_run, name=f"gordo-barrier-{name}", daemon=True)
        t.start()
        if not done.wait(timeout):
            raise BarrierTimeout(
                f"barrier {name!r} timed out after {timeout:.0f}s"
            )
        if error:
            raise BarrierTimeout(
                f"barrier {name!r} failed: {error[0]}"
            ) from error[0]

    # -- teardown ------------------------------------------------------------
    def shutdown(self) -> None:
        """Clean coordination-service disconnect.

        MUST NOT run after a failed barrier: ``jax.distributed.shutdown``
        SIGABRTs when a peer is already dead (reproduced in-container) —
        the resumable exit path uses ``os._exit`` instead, and this method
        turns into a logged no-op."""
        if not self.initialized:
            return
        if self._barrier_failed:
            logger.warning(
                "skipping jax.distributed.shutdown() after barrier failure "
                "(it aborts when a peer is dead); exiting without clean "
                "disconnect"
            )
            return
        import jax

        jax.distributed.shutdown()
        self.initialized = False
