"""Multi-host runtime: process-spanning meshes and sharded fleet builds.

The v5e-64 north star is a 16-host slice.  A single-host ``build-project``
can only drive it as 16 independent jobs; this package turns the fleet
engine into ONE multi-process program (the pjit-paper / Podracer pattern):

- :mod:`~gordo_tpu.distributed.runtime` — coordinator/worker bring-up
  around ``jax.distributed.initialize`` (CLI spec or ``GORDO_*`` env
  vars), global-mesh construction with the ``"models"`` axis spanning
  hosts, a coordination-service barrier with timeout (worker-death
  detection), and clean shutdown.
- :mod:`~gordo_tpu.distributed.partition` — deterministic
  process-sharding of the machine list (per-signature contiguous
  slices), plus the per-shard resumable state file.
- :mod:`~gordo_tpu.distributed.launcher` — fork N local worker
  processes with per-process virtual CPU devices: the simulated-
  multiprocess mechanism behind ``scripts/multihost_dryrun.py`` (same
  idea as the driver's ``dryrun_multichip``, but with REAL cross-process
  ``jax.distributed`` init).
"""

from gordo_tpu.distributed.launcher import (  # noqa: F401
    launch_workers,
    pick_free_port,
    wait_all,
)
from gordo_tpu.distributed.partition import (  # noqa: F401
    EXIT_SHARD_RESUMABLE,
    ProcessShard,
    ShardState,
    max_processes,
    partition_machines,
    process_shard,
)
from gordo_tpu.distributed.runtime import (  # noqa: F401
    BarrierTimeout,
    DistributedConfig,
    DistributedRuntime,
    parse_multihost_spec,
)
