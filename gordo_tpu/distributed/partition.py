"""Deterministic process-sharding of the machine list.

Every worker process computes the SAME partition from the same project
config (no coordination round): machines bucket by fleet signature
exactly as the build plan does, each bucket splits into up to
``num_processes`` near-equal CONTIGUOUS slices (same-signature machines
stay grouped so each process still trains them as few stacked programs;
slicing finer than machine granularity is impossible, which is why the
workflow emitter refuses ``N > machine count``), and slices deal
longest-first onto the least-loaded process with index tie-breaks.  The
result is disjoint, exhaustive, and independent of machine-list order
(buckets sort by signature, as in the plan; members by name).

Artifact/metadata layout is byte-identical to the single-host path by
construction: each process runs the ordinary ``build_project`` on its
shard, and per-machine fleet builds are bit-identical regardless of
bucket membership (the RNG-parity contract, ``docs/architecture.md``) —
so which process builds a machine can't change what lands on disk.

Resumability: each shard owns a state file under
``<output_dir>/.gordo-shards/`` recording its machine list and what
completed.  A worker killed mid-build leaves ``completed ⊂ machines``
with status ``running``; survivors notice via barrier timeout, mark
their state ``resumable``, and exit :data:`EXIT_SHARD_RESUMABLE` — a
re-run of the same spec re-derives the identical partition and the
config-hash registry turns every already-built machine into a cache hit,
so only the dead shard's remainder trains.
"""

from __future__ import annotations

import json
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

from gordo_tpu import telemetry

logger = logging.getLogger(__name__)

_SHARD_RESUMABLE_TOTAL = telemetry.counter(
    "gordo_shard_resumable_total",
    "Shard states marked resumable (peer death / failed machines)",
)

#: exit code of a worker whose shard is incomplete but resumable (a peer
#: died / barrier timed out).  BSD EX_TEMPFAIL: "retry the same command".
EXIT_SHARD_RESUMABLE = 75

SHARD_STATE_DIR = ".gordo-shards"


def _signature_of(machine: Any) -> str:
    """A machine's partition bucket signature: the ``fleet_signature``
    attribute when the object carries one (the serving tier's name-only
    atoms — precomputed so the serve path never imports the build
    plane), else the build plan's config-derived signature."""
    sig = getattr(machine, "fleet_signature", None)
    if sig is not None:
        return sig
    from gordo_tpu.workflow.generator import _fleet_signature

    return _fleet_signature(machine)


def _bucket_slices(machines: Sequence[Any], num_processes: int):
    """Work units in deterministic order: signature buckets (sorted, as in
    the build plan), each split into up to ``num_processes`` near-equal
    contiguous slices of its name-sorted members."""
    buckets: Dict[str, List[Any]] = {}
    for m in machines:
        buckets.setdefault(_signature_of(m), []).append(m)
    out: List[List[Any]] = []
    for _, members in sorted(buckets.items()):
        members = sorted(members, key=lambda m: m.name)
        n_slices = min(num_processes, len(members))
        base, rem = divmod(len(members), n_slices)
        start = 0
        for i in range(n_slices):
            size = base + (1 if i < rem else 0)
            out.append(members[start : start + size])
            start += size
    return out


def max_processes(machines: Sequence[Any]) -> int:
    """Largest useful process count: machines are the atoms of the
    partition, so it is the machine count.  More processes than machines
    means idle workers that still hold every barrier — the workflow
    emitter refuses such specs."""
    return len(machines)


def partition_machines(
    machines: Sequence[Any],
    num_processes: int,
) -> List[List[Any]]:
    """Disjoint, exhaustive, deterministic machine shards — one per process.

    Per-signature contiguous slices deal longest-first onto the
    least-loaded process (machine count), process index breaking ties.
    Every process calling this with the same machine list and
    ``num_processes`` gets the same answer.
    """
    if num_processes < 1:
        raise ValueError(f"num_processes must be >= 1, got {num_processes}")
    shards: List[List[Any]] = [[] for _ in range(num_processes)]
    slices = _bucket_slices(machines, num_processes)
    # stable longest-first: sort key is (-len, first machine name)
    order = sorted(
        range(len(slices)),
        key=lambda i: (-len(slices[i]), slices[i][0].name),
    )
    for i in order:
        target = min(range(num_processes), key=lambda p: (len(shards[p]), p))
        shards[target].extend(slices[i])
    return shards


def process_shard(
    machines: Sequence[Any],
    num_processes: int,
    process_id: int,
    output_dir: Optional[str] = None,
) -> "ProcessShard":
    """This process's shard of the project (see :func:`partition_machines`)."""
    if not 0 <= process_id < num_processes:
        raise ValueError(
            f"process_id {process_id} outside [0, {num_processes})"
        )
    shards = partition_machines(machines, num_processes)
    return ProcessShard(
        machines=shards[process_id],
        process_id=process_id,
        num_processes=num_processes,
        state=(
            ShardState(output_dir, process_id, num_processes)
            if output_dir
            else None
        ),
    )


@dataclass
class ProcessShard:
    """One process's slice of the machine list (+ optional state file)."""

    machines: List[Any]
    process_id: int
    num_processes: int
    state: Optional["ShardState"] = None

    @property
    def names(self) -> List[str]:
        return [m.name for m in self.machines]


@dataclass
class ShardState:
    """Per-shard resumable progress, one JSON file per (pid, n).

    Written atomically (tmp + rename) on every transition so a SIGKILL
    can never leave a torn document; the staleness check is the re-run
    reading ``completed`` and finding everything already registry-cached.
    """

    output_dir: str
    process_id: int
    num_processes: int
    machines: List[str] = field(default_factory=list)
    completed: List[str] = field(default_factory=list)
    status: str = "pending"  # pending | running | done | resumable

    @property
    def path(self) -> str:
        return os.path.join(
            self.output_dir,
            SHARD_STATE_DIR,
            f"shard-{self.process_id:03d}-of-{self.num_processes:03d}.json",
        )

    def start(self, machine_names: Sequence[str]) -> None:
        prior = self.load(
            self.output_dir, self.process_id, self.num_processes
        )
        if prior is not None and sorted(prior.machines) == sorted(machine_names):
            # resuming the same shard: keep the completed history so an
            # operator (or the dryrun) can see what the re-run skipped
            self.completed = list(prior.completed)
        else:
            self.completed = []
        self.machines = list(machine_names)
        self.status = "running"
        self._write()

    def record(self, machine_name: str) -> None:
        if machine_name not in self.completed:
            self.completed.append(machine_name)
            self._write()

    def finish(self) -> None:
        self.status = "done"
        self._write()

    def mark_resumable(self, reason: str = "") -> None:
        self.status = "resumable"
        _SHARD_RESUMABLE_TOTAL.inc()
        # one structured line per transition: a shard going resumable is
        # the multi-host failure signal operators grep for
        telemetry.log_event(
            logger, "shard_resumable",
            process_id=self.process_id,
            num_processes=self.num_processes,
            completed=len(self.completed),
            machines=len(self.machines),
            reason=repr(reason)[:120],
        )
        self._write(extra={"reason": reason})

    def _write(self, extra: Optional[Dict[str, Any]] = None) -> None:
        doc = {
            "process_id": self.process_id,
            "num_processes": self.num_processes,
            "machines": self.machines,
            "completed": self.completed,
            "status": self.status,
            "updated": time.time(),
        }
        if extra:
            doc.update(extra)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        tmp = f"{self.path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=2)
        os.replace(tmp, self.path)

    @classmethod
    def load(
        cls, output_dir: str, process_id: int, num_processes: int
    ) -> Optional["ShardState"]:
        state = cls(output_dir, process_id, num_processes)
        try:
            with open(state.path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            return None
        state.machines = list(doc.get("machines", []))
        state.completed = list(doc.get("completed", []))
        state.status = doc.get("status", "pending")
        return state
