"""Fork-N-local-processes launcher: the simulated multi-host slice.

Real deployments get one process per host from the orchestrator (the
Indexed-Job manifest ``workflow generate --multihost N`` emits).  For
development and the CPU dryrun, this module IS the orchestrator: it forks
N local worker processes, each pinned to its own
``--xla_force_host_platform_device_count`` virtual-CPU backend, wired
together with the same ``GORDO_*`` env contract — so
``jax.distributed.initialize`` runs for real across process boundaries
(coordination service, heartbeats, barriers), which is strictly more
faithful than the single-process ``dryrun_multichip`` device simulation.
"""

from __future__ import annotations

import logging
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional, Sequence

from gordo_tpu.distributed.runtime import (
    ENV_BARRIER_TIMEOUT,
    ENV_COORDINATOR,
    ENV_LOCAL_DEVICES,
    ENV_NUM_PROCESSES,
    ENV_PROCESS_ID,
)

logger = logging.getLogger(__name__)


def pick_free_port(host: str = "127.0.0.1") -> int:
    """An OS-assigned free TCP port (bind-to-0 then close; the tiny race
    window is fine for a dev-box dryrun)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host, 0))
        return s.getsockname()[1]


def worker_env(
    process_id: int,
    num_processes: int,
    coordinator: str,
    local_devices: int = 2,
    barrier_timeout: Optional[float] = None,
    base_env: Optional[Dict[str, str]] = None,
    compile_cache_dir: Optional[str] = None,
) -> Dict[str, str]:
    """Environment for one simulated worker: the ``GORDO_*`` multi-host
    contract plus a CPU backend with ``local_devices`` virtual devices
    (set BEFORE the child's jax initializes — the whole reason launching
    is process-granular).

    ``compile_cache_dir``: point every worker's persistent XLA
    compilation cache (``GORDO_COMPILE_CACHE_DIR``) at one shared path,
    so the N forked processes compile each fleet program ONCE between
    them instead of N times — the same wiring the generated multi-host
    Indexed Job gets from its shared cache volume.
    """
    env = dict(os.environ if base_env is None else base_env)
    env[ENV_COORDINATOR] = coordinator
    env[ENV_NUM_PROCESSES] = str(num_processes)
    env[ENV_PROCESS_ID] = str(process_id)
    env[ENV_LOCAL_DEVICES] = str(local_devices)
    if barrier_timeout is not None:
        env[ENV_BARRIER_TIMEOUT] = str(barrier_timeout)
    if compile_cache_dir is not None:
        env["GORDO_COMPILE_CACHE_DIR"] = compile_cache_dir
    env["JAX_PLATFORMS"] = "cpu"
    # replace (not append) any inherited device-count flag: each worker
    # must see exactly its own count
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f
    ]
    flags.append(f"--xla_force_host_platform_device_count={local_devices}")
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def launch_workers(
    argv: Sequence[str],
    num_processes: int,
    coordinator: Optional[str] = None,
    local_devices: int = 2,
    barrier_timeout: Optional[float] = None,
    stdout_dir: Optional[str] = None,
    compile_cache_dir: Optional[str] = None,
) -> List[subprocess.Popen]:
    """Fork ``num_processes`` copies of ``argv`` wired as one multi-host
    job.  Returns the live Popen list (index == process_id).

    ``stdout_dir``: when given, worker i's combined output streams to
    ``worker-i.log`` there (the dryrun tails these on failure); otherwise
    workers inherit this process's stdio.
    """
    coordinator = coordinator or f"127.0.0.1:{pick_free_port()}"
    procs: List[subprocess.Popen] = []
    for pid in range(num_processes):
        env = worker_env(
            pid, num_processes, coordinator,
            local_devices=local_devices, barrier_timeout=barrier_timeout,
            compile_cache_dir=compile_cache_dir,
        )
        if stdout_dir:
            os.makedirs(stdout_dir, exist_ok=True)
            out = open(os.path.join(stdout_dir, f"worker-{pid}.log"), "wb")
        else:
            out = None
        procs.append(
            subprocess.Popen(
                list(argv),
                env=env,
                stdout=out,
                stderr=subprocess.STDOUT if out else None,
            )
        )
    return procs


def wait_all(
    procs: Sequence[subprocess.Popen], timeout: float = 600.0
) -> List[int]:
    """Wait for every worker; on deadline, kill stragglers (rc -9).

    Returns per-worker exit codes.  Callers decide what codes mean —
    the dryrun treats :data:`~gordo_tpu.distributed.partition.
    EXIT_SHARD_RESUMABLE` as the expected survivor outcome of a killed
    peer."""
    deadline = time.time() + timeout
    codes: List[int] = []
    for p in procs:
        remaining = max(0.0, deadline - time.time())
        try:
            codes.append(p.wait(timeout=remaining))
        except subprocess.TimeoutExpired:
            logger.error("worker pid=%s overran the deadline; killing", p.pid)
            p.kill()
            codes.append(p.wait())
    return codes


def python_argv(*args: str) -> List[str]:
    """``[sys.executable, *args]`` — the interpreter the launcher itself
    runs under, so venvs survive the fork."""
    return [sys.executable, *args]
