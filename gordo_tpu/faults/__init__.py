"""Deterministic, seedable fault-injection plane.

Every robustness property the fleet claims (quarantine, failover,
deadline shedding, crash-safe writes) is exercised by *injecting* the
fault it defends against, at the seam where the real fault would land.
The plane is off by default: ``_PLANE`` is module-level ``None`` and
:func:`check` is a single attribute load + ``is None`` test, so product
seams pay nothing when no spec is configured.  Injection points are
seam-level (per pack open, per HTTP request, per barrier wait) — never
inside hot loop bodies, which ``scripts/lint.py`` gates.

Spec grammar (``GORDO_FAULTS`` env var or :func:`configure`)::

    spec     := clause (";" clause)*
    clause   := "seed=" int
              | point "=" mode [":" rate] [":" params]
    point    := dotted name, e.g. "pack.open", "http.request"
    rate     := float in [0, 1]        (default 1.0 — always fire)
    params   := key "=" value ("," key "=" value)*
                keys: ms (latency millis), times (max fires),
                      after (skip the first N matching calls),
                      match (substring filter on context values)

Example::

    GORDO_FAULTS="seed=7;pack.open=eio:0.5;http.request=latency:1:ms=40"

Registered points and their modes (the seams translate
:class:`InjectedFault` into the domain's native failure):

=================  =============================================
point              modes
=================  =============================================
pack.open          eio, corrupt, truncate
pack.read          eio, corrupt
artifact.write     enospc, crash  (crash = before the atomic rename)
scores.compact     crash  (before the period flip — tmp is durable,
                   the index still points at the chunk segments)
http.request       latency, blackhole, reset, http_500, http_503
server.request     latency, http_500, reset
replica.scatter    dead
watchman.scrape    blackhole
barrier.wait       peer_loss
stream.ingest      latency, reset, http_503  (fires BEFORE state
                   mutation — a failed ingest never half-applies)
stream.push        disconnect  (transport killed mid-frame),
                   slow_consumer  (writer stalls until its queue
                   overflows and the hub disconnects it)
=================  =============================================

Determinism: every rule draws from its own ``random.Random`` seeded
from ``(seed, point, mode, rule-index)``, and per-rule call counters are
lock-protected, so the same spec over the same call sequence fires the
same faults — the chaos suite's replayability contract.
"""

from __future__ import annotations

import errno
import logging
import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from gordo_tpu import telemetry

__all__ = [
    "FaultSpecError",
    "InjectedFault",
    "FaultRule",
    "FaultPlane",
    "parse_spec",
    "configure",
    "clear",
    "enabled",
    "check",
    "injected",
]

ENV_FAULTS = "GORDO_FAULTS"

logger = logging.getLogger(__name__)

_INJECTED_TOTAL = telemetry.counter(
    "gordo_faults_injected_total",
    "Faults fired by the injection plane",
    ("point", "mode"),
)


class FaultSpecError(ValueError):
    """A ``GORDO_FAULTS`` spec that does not parse."""


class InjectedFault(Exception):
    """An injected fault, raised at a seam.

    Seams translate this into the domain's native failure (a pack seam
    maps ``corrupt`` to ``PackCorruptError``, an HTTP seam maps
    ``reset`` to a connection error) so downstream code exercises the
    exact path a real fault would take.
    """

    def __init__(self, point: str, mode: str, detail: str = ""):
        self.point = point
        self.mode = mode
        self.detail = detail
        super().__init__(
            f"injected fault {mode!r} at {point}"
            + (f" ({detail})" if detail else "")
        )


@dataclass
class FaultRule:
    point: str
    mode: str
    rate: float = 1.0
    ms: float = 0.0
    times: Optional[int] = None
    after: int = 0
    match: Optional[str] = None
    _calls: int = field(default=0, repr=False)
    _fired: int = field(default=0, repr=False)
    _rng: random.Random = field(default=None, repr=False)  # type: ignore

    def should_fire(self, ctx: Dict[str, Any]) -> bool:
        """Decide (and record) whether this rule fires for one call.

        Caller holds the plane lock — counters and the RNG draw are
        part of the deterministic schedule and must be serialized.
        """
        if self.match is not None and not any(
            self.match in str(v) for v in ctx.values()
        ):
            return False
        self._calls += 1
        if self._calls <= self.after:
            return False
        if self.times is not None and self._fired >= self.times:
            return False
        if self.rate < 1.0 and self._rng.random() >= self.rate:
            return False
        self._fired += 1
        return True


class FaultPlane:
    """A parsed, seeded fault schedule; install with :func:`configure`."""

    def __init__(self, rules: List[FaultRule], seed: int = 0):
        self.seed = seed
        self.rules: Dict[str, List[FaultRule]] = {}
        self._lock = threading.Lock()
        for i, rule in enumerate(rules):
            rule._rng = random.Random(f"{seed}:{rule.point}:{rule.mode}:{i}")
            self.rules.setdefault(rule.point, []).append(rule)

    def fire(self, point: str, ctx: Dict[str, Any]) -> None:
        rules = self.rules.get(point)
        if not rules:
            return
        for rule in rules:
            with self._lock:
                firing = rule.should_fire(ctx)
            if not firing:
                continue
            _INJECTED_TOTAL.inc(1.0, point, rule.mode)
            telemetry.log_event(
                logger, "fault.injected", point=point, mode=rule.mode,
                **{k: str(v) for k, v in ctx.items()},
            )
            if rule.mode == "latency":
                time.sleep(rule.ms / 1000.0)
                continue
            if rule.mode == "eio":
                raise OSError(errno.EIO, f"injected EIO at {point}")
            if rule.mode == "enospc":
                raise OSError(
                    errno.ENOSPC, f"injected disk-full at {point}"
                )
            raise InjectedFault(point, rule.mode, detail=str(ctx or ""))

    def stats(self) -> Dict[str, Dict[str, int]]:
        out: Dict[str, Dict[str, int]] = {}
        with self._lock:
            for point, rules in self.rules.items():
                for rule in rules:
                    key = f"{point}:{rule.mode}"
                    out[key] = {"calls": rule._calls, "fired": rule._fired}
        return out


def _parse_params(raw: str, rule: FaultRule) -> None:
    for pair in raw.split(","):
        if not pair:
            continue
        if "=" not in pair:
            raise FaultSpecError(f"bad fault param {pair!r} (want key=value)")
        key, value = pair.split("=", 1)
        if key == "ms":
            rule.ms = float(value)
        elif key == "times":
            rule.times = int(value)
        elif key == "after":
            rule.after = int(value)
        elif key == "match":
            rule.match = value
        else:
            raise FaultSpecError(f"unknown fault param {key!r}")


def parse_spec(spec: str) -> FaultPlane:
    seed = 0
    rules: List[FaultRule] = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if "=" not in clause:
            raise FaultSpecError(
                f"bad fault clause {clause!r} (want point=mode[:rate][:params])"
            )
        point, rhs = clause.split("=", 1)
        point = point.strip()
        if point == "seed":
            try:
                seed = int(rhs)
            except ValueError:
                raise FaultSpecError(f"bad seed {rhs!r}") from None
            continue
        parts = rhs.split(":")
        rule = FaultRule(point=point, mode=parts[0].strip())
        if not rule.mode:
            raise FaultSpecError(f"empty mode in clause {clause!r}")
        if len(parts) > 1 and parts[1]:
            try:
                rule.rate = float(parts[1])
            except ValueError:
                raise FaultSpecError(
                    f"bad rate {parts[1]!r} in clause {clause!r}"
                ) from None
            if not 0.0 <= rule.rate <= 1.0:
                raise FaultSpecError(f"rate out of [0,1] in clause {clause!r}")
        if len(parts) > 2:
            _parse_params(":".join(parts[2:]), rule)
        rules.append(rule)
    return FaultPlane(rules, seed=seed)


#: the installed plane; ``None`` means faults are off and every seam's
#: :func:`check` is a single ``is None`` test.
_PLANE: Optional[FaultPlane] = None


def configure(spec: Optional[str] = None) -> Optional[FaultPlane]:
    """Install a fault plane from ``spec`` (or ``GORDO_FAULTS``).

    Passing ``None`` with no env var set clears the plane.  Returns the
    installed plane (or ``None``).
    """
    global _PLANE
    if spec is None:
        spec = os.environ.get(ENV_FAULTS) or None
    _PLANE = parse_spec(spec) if spec else None
    return _PLANE


def clear() -> None:
    global _PLANE
    _PLANE = None


def enabled() -> bool:
    return _PLANE is not None


def plane() -> Optional[FaultPlane]:
    return _PLANE


def check(point: str, **ctx: Any) -> None:
    """Injection point: raise/delay if a fault is scheduled for ``point``.

    The no-plane path is one global load and an ``is None`` test.  Do
    not call this inside hot loop bodies (lint-gated) — register the
    point at the enclosing seam instead.
    """
    plane = _PLANE
    if plane is None:
        return
    plane.fire(point, ctx)


class injected:
    """Context manager installing a plane for a scope (tests)::

        with faults.injected("seed=3;pack.open=eio"):
            ...
    """

    def __init__(self, spec: str):
        self.spec = spec
        self.plane: Optional[FaultPlane] = None
        self._prev: Optional[FaultPlane] = None

    def __enter__(self) -> FaultPlane:
        global _PLANE
        self._prev = _PLANE
        self.plane = parse_spec(self.spec)
        _PLANE = self.plane
        return self.plane

    def __exit__(self, *exc: Any) -> None:
        global _PLANE
        _PLANE = self._prev
        return None


# honor the env var at import so any entrypoint (server, CLI, builder)
# picks the spec up without plumbing; imports stay cheap when unset.
if os.environ.get(ENV_FAULTS):
    configure()
