"""The refresh driver: poll fleet health, select drifting machines under
hysteresis, warm-start rebuild exactly those, wait for the generation to
go live.

Reference pattern: Podracer's continuously-running actor/learner split
(PAPERS.md) — serving telemetry feeds training, training feeds serving,
forever.  The cost model is the point: one cycle's work scales with the
number of DRIFTED machines, never with fleet size.

Interfaces only (the lint-enforced plane boundary):

- health IN: the shard-keyed rollup JSONL files under the artifact dir
  (``telemetry.read_rollups``) or a watchman/server ``/fleet-health``
  HTTP endpoint — never scorer internals;
- models OUT: ``builder.build_project(warm_start=True)``, which
  publishes through ``artifacts.delta_write`` + ``stamp_generation``;
- liveness: ``client.wait_for_generation`` — the same generation
  handshake any external consumer uses.

Selection is hysteretic so one noisy scoring window can't thrash
rebuilds: a machine must be observed ``status=drifting`` on K
CONSECUTIVE health polls (``GORDO_REFRESH_HYSTERESIS``) and sit outside
its per-machine cooldown (``GORDO_REFRESH_COOLDOWN_SECONDS``) before it
is rebuilt.  Selector state persists under
``<output_dir>/.gordo-refresh/state.json`` so ``gordo refresh --once``
(the CronJob face) accumulates streaks across invocations.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional, Sequence

from gordo_tpu import artifacts, telemetry

logger = logging.getLogger(__name__)

# -- knobs (docs/configuration.md "Incremental refresh") --------------------
ENV_HYSTERESIS = "GORDO_REFRESH_HYSTERESIS"
DEFAULT_HYSTERESIS = 2
ENV_COOLDOWN_SECONDS = "GORDO_REFRESH_COOLDOWN_SECONDS"
DEFAULT_COOLDOWN_SECONDS = 900.0
ENV_INTERVAL = "GORDO_REFRESH_INTERVAL"
DEFAULT_INTERVAL = 300.0

#: selector state under the artifact dir — file-per-project, like the
#: telemetry snapshots and health rollups it sits next to
STATE_DIR = ".gordo-refresh"
STATE_FILE = "state.json"

# -- telemetry instruments (docs/observability.md) --------------------------
_CYCLES_TOTAL = telemetry.counter(
    "gordo_refresh_cycles_total",
    "Refresh cycles run, by outcome",
    labels=("outcome",),  # rebuilt | idle | no-health | failed
)
_MACHINES_TOTAL = telemetry.counter(
    "gordo_refresh_machines_total",
    "Machines handled by refresh rebuilds, by path",
    labels=("path",),  # warm | fallback | failed
)
_DRIFT_TO_LIVE_SECONDS = telemetry.histogram(
    "gordo_refresh_drift_to_live_seconds",
    "End-to-end seconds from drift selection to the rebuilt generation "
    "being live (build + publish + reload confirmation)",
    buckets=(0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
             600.0, 1800.0),
)


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def state_path(output_dir: str) -> str:
    return os.path.join(output_dir, STATE_DIR, STATE_FILE)


class DriftSelector:
    """Hysteretic drift selection with per-machine cooldown.

    Pure bookkeeping over health docs — time arrives as an argument, so
    the unit tests never sleep.  ``observe`` returns the machines whose
    drifting streak reached the hysteresis threshold AND whose last
    rebuild is outside the cooldown window; ``mark_rebuilt`` resets the
    streak and starts the cooldown."""

    def __init__(
        self,
        hysteresis: Optional[int] = None,
        cooldown_seconds: Optional[float] = None,
    ):
        self.hysteresis = max(1, (
            _env_int(ENV_HYSTERESIS, DEFAULT_HYSTERESIS)
            if hysteresis is None else int(hysteresis)
        ))
        self.cooldown_seconds = max(0.0, (
            _env_float(ENV_COOLDOWN_SECONDS, DEFAULT_COOLDOWN_SECONDS)
            if cooldown_seconds is None else float(cooldown_seconds)
        ))
        #: {machine: {"streak": int, "last_rebuild": float|None}}
        self._state: Dict[str, Dict[str, Any]] = {}

    def _entry(self, name: str) -> Dict[str, Any]:
        return self._state.setdefault(
            name, {"streak": 0, "last_rebuild": None}
        )

    def observe(self, doc: Dict[str, Any], now: float) -> List[str]:
        """Fold one health doc into the streaks; return the machines
        selected for rebuild.  Machines absent from the doc keep their
        streak (a silent shard is not evidence the drift cleared)."""
        selected: List[str] = []
        for name, entry in (doc.get("machines") or {}).items():
            state = self._entry(name)
            if entry.get("status") == "drifting":
                state["streak"] = int(state["streak"]) + 1
            else:
                state["streak"] = 0
        for name, state in self._state.items():
            if state["streak"] < self.hysteresis:
                continue
            last = state.get("last_rebuild")
            if last is not None and now - float(last) < self.cooldown_seconds:
                continue
            selected.append(name)
        return sorted(selected)

    def mark_rebuilt(self, names: Sequence[str], now: float) -> None:
        for name in names:
            state = self._entry(name)
            state["streak"] = 0
            state["last_rebuild"] = float(now)

    # -- persistence (the --once / CronJob face needs streaks to survive
    # -- process exits; atomic tmp+rename like every other sidecar) ---------
    def to_doc(self) -> Dict[str, Any]:
        return {
            "gordo-refresh-state": 1,
            "hysteresis": self.hysteresis,
            "cooldown-seconds": self.cooldown_seconds,
            "machines": {n: dict(s) for n, s in self._state.items()},
        }

    def save(self, path: str) -> None:
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(self.to_doc(), fh, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            logger.exception("refresh state save failed: %s", path)

    @classmethod
    def load(
        cls,
        path: str,
        hysteresis: Optional[int] = None,
        cooldown_seconds: Optional[float] = None,
    ) -> "DriftSelector":
        """A selector seeded from ``path`` when it exists (torn/corrupt
        files start fresh — hysteresis only delays a rebuild, never
        loses one)."""
        selector = cls(
            hysteresis=hysteresis, cooldown_seconds=cooldown_seconds
        )
        try:
            with open(path) as fh:
                doc = json.load(fh)
            for name, state in (doc.get("machines") or {}).items():
                selector._state[name] = {
                    "streak": int(state.get("streak", 0)),
                    "last_rebuild": state.get("last_rebuild"),
                }
        except (OSError, ValueError):
            pass
        return selector


@dataclasses.dataclass
class RefreshConfig:
    """One refresh deployment's wiring: the machine configs it may
    rebuild, where artifacts live, and which health surface it polls."""

    machines: Sequence[Any]
    output_dir: str
    model_register_dir: Optional[str] = None
    project: str = "project"
    #: HTTP health surface (watchman or server base URL); None polls the
    #: rollup files under ``output_dir`` instead — no HTTP needed
    health_url: Optional[str] = None
    #: server base URL to confirm the generation went live on (via the
    #: client's wait_for_generation handshake); None skips confirmation
    server_url: Optional[str] = None
    hysteresis: Optional[int] = None
    cooldown_seconds: Optional[float] = None
    wait_timeout: float = 120.0
    #: extra build_project kwargs (mesh, max_bucket_size, ...)
    build_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)


def read_health(cfg: RefreshConfig) -> Optional[Dict[str, Any]]:
    """The current fleet-health doc over a public interface: HTTP when
    ``cfg.health_url`` is set, else the rollup files under the artifact
    dir.  None when no health is observable (nothing to select from)."""
    if not cfg.health_url:
        return telemetry.read_rollups(cfg.output_dir)
    import urllib.request

    base = cfg.health_url.rstrip("/")
    candidates = [
        f"{base}/gordo/v0/{cfg.project}/fleet-health",
        f"{base}/fleet-health",  # watchman surface
    ]
    last_err: Optional[Exception] = None
    for candidate in candidates:
        try:
            with urllib.request.urlopen(candidate, timeout=30) as resp:
                doc = json.loads(resp.read().decode())
            if doc.get("gordo-fleet-health") or doc.get("machines"):
                return doc
        except Exception as exc:  # 404 on one surface, conn errors
            last_err = exc
    logger.warning(
        "fleet-health fetch failed from %s: %s", candidates, last_err
    )
    return None


def _wait_live(cfg: RefreshConfig, generation: int) -> Optional[Dict]:
    """Block until every serving replica reports ``generation`` (the
    client's public handshake).  Returns the per-replica map, or None on
    timeout — the rebuild is still published; confirmation is what
    failed, and the summary says so."""
    from gordo_tpu.client import Client

    client = Client(
        project=cfg.project, base_url=cfg.server_url,
        timeout=cfg.wait_timeout,
    )
    try:
        return client.wait_for_generation(
            generation, timeout=cfg.wait_timeout
        )
    except TimeoutError as exc:
        logger.warning("generation %d not confirmed live: %s",
                       generation, exc)
        return None


def refresh_once(
    cfg: RefreshConfig,
    selector: Optional[DriftSelector] = None,
    now: Optional[float] = None,
) -> Dict[str, Any]:
    """One refresh cycle: poll → select → warm rebuild → publish → wait
    for the flip.  Returns a summary dict (the CLI prints it as JSON).

    Pass a :class:`DriftSelector` to keep streak state in-process (the
    ``--interval`` loop); without one, state loads from and saves to
    ``<output_dir>/.gordo-refresh/state.json`` so repeated ``--once``
    invocations (the CronJob) accumulate hysteresis correctly."""
    from gordo_tpu.builder import build_project

    t_cycle = time.time()
    now = t_cycle if now is None else now
    path = state_path(cfg.output_dir)
    if selector is None:
        selector = DriftSelector.load(
            path, hysteresis=cfg.hysteresis,
            cooldown_seconds=cfg.cooldown_seconds,
        )

    doc = read_health(cfg)
    if doc is None:
        _CYCLES_TOTAL.inc(1.0, "no-health")
        return {"outcome": "no-health", "selected": []}

    selected = selector.observe(doc, now)
    by_name = {m.name: m for m in cfg.machines}
    subset = [by_name[n] for n in selected if n in by_name]
    unknown = [n for n in selected if n not in by_name]
    if unknown:
        logger.warning(
            "drifting machine(s) not in this refresh deployment's "
            "config: %s", unknown,
        )
    drifting = sorted(
        n for n, e in (doc.get("machines") or {}).items()
        if e.get("status") == "drifting"
    )
    if not subset:
        selector.save(path)
        _CYCLES_TOTAL.inc(1.0, "idle")
        return {
            "outcome": "idle", "selected": [], "drifting": drifting,
            "unknown": unknown,
        }

    logger.info(
        "refresh cycle: rebuilding %d drifted machine(s): %s",
        len(subset), [m.name for m in subset],
    )
    result = build_project(
        subset,
        cfg.output_dir,
        model_register_dir=cfg.model_register_dir,
        warm_start=True,
        **cfg.build_kwargs,
    )
    rebuilt = sorted(result.fleet_built + result.single_built)
    _MACHINES_TOTAL.inc(float(len(result.warm_started)), "warm")
    fallback_built = [n for n in result.warm_fallbacks if n in set(rebuilt)]
    _MACHINES_TOTAL.inc(float(len(fallback_built)), "fallback")
    _MACHINES_TOTAL.inc(float(len(result.failed)), "failed")

    generation = result.generation
    if generation is None:
        generation = artifacts.read_generation(cfg.output_dir)
    confirmed = None
    if cfg.server_url and generation:
        confirmed = _wait_live(cfg, int(generation))

    latency = time.time() - t_cycle
    if rebuilt:
        # drift → build → publish → (confirmed) live, end to end
        _DRIFT_TO_LIVE_SECONDS.observe(latency)
    selector.mark_rebuilt(rebuilt, time.time() if now is t_cycle else now)
    selector.save(path)
    _CYCLES_TOTAL.inc(1.0, "failed" if result.failed else "rebuilt")

    summary = {
        "outcome": "failed" if result.failed else "rebuilt",
        "selected": [m.name for m in subset],
        "drifting": drifting,
        "rebuilt": rebuilt,
        "warm_started": sorted(result.warm_started),
        "warm_fallbacks": dict(result.warm_fallbacks),
        "failed": dict(result.failed),
        "generation": int(generation) if generation else None,
        "live_confirmed": confirmed is not None,
        "seconds": latency,
    }
    if getattr(result, "ingest", None) is not None:
        # the refresh rides the builder's ingest plane (warm_start chunks
        # load through it too) — surface the fetch-dedup accounting
        summary["ingest"] = dict(result.ingest)
    return summary


def run_refresh(
    cfg: RefreshConfig,
    interval: Optional[float] = None,
    max_cycles: Optional[int] = None,
    sleep=time.sleep,
) -> List[Dict[str, Any]]:
    """The continuous loop: ``refresh_once`` every ``interval`` seconds
    (default ``GORDO_REFRESH_INTERVAL``), sharing one selector so
    hysteresis streaks span cycles without touching disk between them.
    ``max_cycles`` bounds the loop (tests; ``--once`` uses 1)."""
    interval = (
        _env_float(ENV_INTERVAL, DEFAULT_INTERVAL)
        if interval is None else float(interval)
    )
    selector = DriftSelector.load(
        state_path(cfg.output_dir), hysteresis=cfg.hysteresis,
        cooldown_seconds=cfg.cooldown_seconds,
    )
    summaries: List[Dict[str, Any]] = []
    cycle = 0
    while True:
        summaries.append(refresh_once(cfg, selector=selector))
        cycle += 1
        if max_cycles is not None and cycle >= max_cycles:
            return summaries
        sleep(interval)
