"""Drift-driven incremental refresh: the consumer that turns full-fleet
batch rebuilds into targeted, O(drifted) warm-start refreshes.

The loop closes the continuous cycle the serving and builder planes
already expose ends of: scoring feeds fleet-health sketches, drift
selects machines, the builder warm-starts exactly those from the
previous generation's params, ``delta_write`` flips the generation, and
live servers delta-reload the touched packs — no restart anywhere.

Boundary contract (enforced by ``scripts/lint.py``): this plane talks to
serving ONLY over its file and HTTP interfaces — fleet-health rollup
files / watchman ``/fleet-health``, and the client's generation
handshake.  Never server internals.
"""

from gordo_tpu.refresh.loop import (  # noqa: F401
    DriftSelector,
    RefreshConfig,
    read_health,
    refresh_once,
    run_refresh,
)

__all__ = [
    "DriftSelector",
    "RefreshConfig",
    "read_health",
    "refresh_once",
    "run_refresh",
]
