"""Endpoint polling.

Reference equivalent: ``gordo_components/watchman/endpoints_status.py`` —
build the expected endpoint list from the project config's machine names,
poll each ML server's ``/healthcheck`` and ``/metadata``, and record
per-endpoint health + metadata.  The reference watched kubernetes events
to discover pods; here the server list is explicit config (one TPU-host
server serves many machines) and discovery is a poll of each server's
project index.
"""

from __future__ import annotations

import asyncio
import dataclasses
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import aiohttp

from gordo_tpu import faults, telemetry

API_PREFIX = "/gordo/v0"

#: scrape failures were previously SILENT in the merged exposition — a
#: target contributing nothing is indistinguishable from a target with
#: no series unless someone reads watchman's logs.  Now every failed
#: target scrape counts here (labelled ``target=`` like watchman's
#: other per-target series: the merge adds ``instance="watchman"`` to
#: watchman's own samples, so an ``instance`` label here would collide)
#: and the last error text is republished in the status doc's
#: ``scrape-status``.
_SCRAPE_FAILURES = telemetry.counter(
    "gordo_watchman_scrape_failures_total",
    "Failed /metrics scrapes of target servers, by target base url",
    labels=("target",),
)


@dataclasses.dataclass
class EndpointStatus:
    """One machine endpoint's latest observed state."""

    machine: str
    endpoint: str                       # path under the ambassador/base url
    base_url: Optional[str]             # which server answered (None: nobody)
    healthy: bool
    metadata: Dict[str, Any]
    last_checked: float
    last_seen: Optional[float] = None

    def to_json(self) -> Dict[str, Any]:
        return {
            "endpoint": self.endpoint,
            "target-name": self.machine,
            "healthy": self.healthy,
            "endpoint-metadata": self.metadata,
            "last-checked": self.last_checked,
            "last-seen": self.last_seen,
        }


async def _check_one(
    session: aiohttp.ClientSession,
    project: str,
    machine: str,
    base_urls: Sequence[str],
    timeout: float,
) -> EndpointStatus:
    path = f"{API_PREFIX}/{project}/{machine}/"
    now = time.time()
    for base in base_urls:
        try:
            async with session.get(
                f"{base}{path}healthcheck",
                timeout=aiohttp.ClientTimeout(total=timeout),
            ) as resp:
                if resp.status != 200:
                    continue
            # healthcheck decided health; a slow/failed metadata fetch only
            # degrades metadata, it must not flip the machine unhealthy
            try:
                async with session.get(
                    f"{base}{path}metadata",
                    timeout=aiohttp.ClientTimeout(total=timeout),
                ) as resp:
                    meta = await resp.json() if resp.status == 200 else {}
            except (aiohttp.ClientError, asyncio.TimeoutError):
                meta = {}
            return EndpointStatus(
                machine=machine,
                endpoint=path,
                base_url=base,
                healthy=True,
                metadata=meta,
                last_checked=now,
                last_seen=now,
            )
        except (aiohttp.ClientError, asyncio.TimeoutError):
            continue
    return EndpointStatus(
        machine=machine,
        endpoint=path,
        base_url=None,
        healthy=False,
        metadata={},
        last_checked=now,
    )


async def discover_machines(
    project: str,
    base_urls: Sequence[str],
    timeout: float = 5.0,
    session: Optional[aiohttp.ClientSession] = None,
) -> List[str]:
    """Machines each target server reports in its project index.

    The reference discovered endpoints from kubernetes namespace events;
    here one server hosts many machines, so the server's own
    ``GET /gordo/v0/<project>/`` index is the discovery source — machines
    built/loaded after watchman start appear on the next poll.
    """
    names, _ = await discover_machines_ex(
        project, base_urls, timeout=timeout, session=session
    )
    return names


async def discover_machines_ex(
    project: str,
    base_urls: Sequence[str],
    timeout: float = 5.0,
    session: Optional[aiohttp.ClientSession] = None,
    artifact_formats: Optional[Dict[str, str]] = None,
    topology: Optional[Dict[str, Dict[str, Any]]] = None,
) -> "tuple[List[str], int]":
    """Like :func:`discover_machines` but also reports how many targets
    answered their index at all — callers evicting machines absent from
    discovery must distinguish "every index omits this machine" from "no
    index was reachable this cycle".

    ``artifact_formats``: optional dict the poll fills with each
    responding target's reported ``artifact-format`` (``v2-packs`` |
    ``v1-dirs``) — the fleet-wide artifact-discovery surface watchman
    republishes, free-riding on the index responses already fetched.

    ``topology``: optional dict the poll fills with each responding
    target's routing identity — ``{"shard-index", "shard-count",
    "fleet-generation", "machines"}`` (shard fields absent for an
    unsharded target) — the one-endpoint routing-topology surface
    watchman republishes so operators see which replica owns which
    machines, and which artifact generation each replica serves, without
    querying every server."""
    own_session = session is None
    session = session or aiohttp.ClientSession()
    names: List[str] = []
    n_responding = 0
    try:
        for base in base_urls:
            try:
                faults.check("watchman.scrape", target=base)
                async with session.get(
                    f"{base}{API_PREFIX}/{project}/",
                    timeout=aiohttp.ClientTimeout(total=timeout),
                ) as resp:
                    if resp.status != 200:
                        continue
                    body = await resp.json()
            except faults.InjectedFault:
                continue  # blackholed target: indistinguishable from down
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
                continue
            n_responding += 1
            if artifact_formats is not None and body.get("artifact-format"):
                artifact_formats[base] = str(body["artifact-format"])
            if topology is not None:
                entry: Dict[str, Any] = {
                    "machines": list(body.get("machines") or []),
                }
                if body.get("fleet-generation") is not None:
                    entry["fleet-generation"] = int(body["fleet-generation"])
                shard = body.get("serve-shard") or {}
                if shard:
                    entry["shard-index"] = int(shard.get("index", 0))
                    entry["shard-count"] = int(shard.get("count", 1))
                topology[base] = entry
            for name in body.get("machines") or []:
                if name not in names:
                    names.append(str(name))
    finally:
        if own_session:
            await session.close()
    return names, n_responding


async def scrape_metrics(
    base_urls: Sequence[str],
    timeout: float = 5.0,
    session: Optional[aiohttp.ClientSession] = None,
    extra: Optional[Sequence[Tuple[str, str]]] = None,
    errors: Optional[Dict[str, str]] = None,
) -> Tuple[str, int]:
    """Scrape every target server's ``/metrics`` and merge them into one
    Prometheus exposition with per-target ``instance`` labels.

    Merging is label-tagging, never arithmetic: summing a ``batch_cap``
    gauge across servers would manufacture a number nobody set, so each
    target's series stay distinct under its ``instance=<base_url>``.
    Returns ``(merged_text, n_responding)`` — an unreachable target
    contributes no series, but its failure is no longer silent: it
    counts in ``gordo_watchman_scrape_failures_total{instance=...}``
    (which rides the merged exposition itself) and lands in ``errors``
    when the caller passes a dict (the status doc's per-target
    last-error surface).  ``extra`` adds local ``(instance,
    exposition)`` pairs (e.g. the caller's own registry) to the same
    merge so the output is ONE spec-valid document."""
    own_session = session is None
    session = session or aiohttp.ClientSession()
    pairs: List[Tuple[str, str]] = []
    n_responding = 0
    try:
        async def one(base: str) -> None:
            nonlocal n_responding
            try:
                async with session.get(
                    f"{base}/metrics",
                    timeout=aiohttp.ClientTimeout(total=timeout),
                ) as resp:
                    if resp.status != 200:
                        _SCRAPE_FAILURES.inc(1.0, base)
                        if errors is not None:
                            errors[base] = f"HTTP {resp.status}"
                        return
                    text = await resp.text()
            except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
                _SCRAPE_FAILURES.inc(1.0, base)
                if errors is not None:
                    errors[base] = f"{type(exc).__name__}: {exc}"
                return
            n_responding += 1
            if errors is not None:
                errors.pop(base, None)
            pairs.append((base, text))

        await asyncio.gather(*(one(b) for b in base_urls))
    finally:
        if own_session:
            await session.close()
    pairs.sort()  # deterministic output regardless of response order
    pairs.extend(extra or ())
    return telemetry.merge_expositions(pairs), n_responding


async def fetch_fleet_health(
    project: str,
    base_urls: Sequence[str],
    timeout: float = 5.0,
    session: Optional[aiohttp.ClientSession] = None,
    top: Optional[int] = None,
) -> Tuple[List[Dict[str, Any]], List[str]]:
    """Each target's ``GET /gordo/v0/<project>/fleet-health`` doc.

    Returns ``(docs, responding_targets)`` — per-shard health docs ready
    for :func:`gordo_tpu.telemetry.merge_health_docs` (sketches are
    exactly mergeable, so a sharded tier's merged view equals a
    single-process one).  Unreachable targets contribute nothing; the
    caller reports them via the health poll as usual."""
    own_session = session is None
    session = session or aiohttp.ClientSession()
    docs: List[Dict[str, Any]] = []
    responding: List[str] = []
    try:
        async def one(base: str) -> None:
            url = f"{base}{API_PREFIX}/{project}/fleet-health"
            if top is not None:
                url += f"?top={int(top)}"
            try:
                async with session.get(
                    url, timeout=aiohttp.ClientTimeout(total=timeout)
                ) as resp:
                    if resp.status != 200:
                        return
                    doc = await resp.json()
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError):
                return
            if doc.get("gordo-fleet-health"):
                docs.append(doc)
                responding.append(base)

        await asyncio.gather(*(one(b) for b in base_urls))
    finally:
        if own_session:
            await session.close()
    # deterministic merge order regardless of response arrival
    order = sorted(range(len(responding)), key=lambda i: responding[i])
    return [docs[i] for i in order], sorted(responding)


async def poll_endpoints(
    project: str,
    machines: Sequence[str],
    base_urls: Sequence[str],
    timeout: float = 5.0,
    session: Optional[aiohttp.ClientSession] = None,
) -> List[EndpointStatus]:
    """Poll every machine endpoint once, concurrently."""
    own_session = session is None
    session = session or aiohttp.ClientSession()
    try:
        return list(
            await asyncio.gather(
                *(
                    _check_one(session, project, m, base_urls, timeout)
                    for m in machines
                )
            )
        )
    finally:
        if own_session:
            await session.close()
