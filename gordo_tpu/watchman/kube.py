"""Kubernetes target discovery for watchman.

Reference equivalent: ``gordo_components/watchman`` watched kubernetes
namespace events to discover per-machine ml-server pods.  The TPU-era
topology is one server Deployment per project (many machines each), so
discovery here finds *server Services* by label and hands their URLs to
:class:`~gordo_tpu.watchman.server.Watchman` as targets; machine-level
discovery then rides each server's own project index
(``endpoints_status.discover_machines``).

Like the reference, discovery is event-driven AND polled: a background
WATCH thread streams Service add/modify/delete events into a live
target cache (fleet membership changes propagate within event latency,
not at poll cadence), while the plain list path remains both the
watch-seeding resync and the fallback when watching is off or broken.

Import-gated on the ``kubernetes`` client package (not in the TPU image);
tests fake the module in ``sys.modules`` — the reference mocked the k8s
client the same way (SURVEY.md §5 watchman bullet).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional

logger = logging.getLogger(__name__)


class KubeTargetDiscovery:
    """Resolve ml-server base URLs from Services in a namespace.

    Services are selected by ``label_selector`` (default: the project
    label the workflow generator stamps on server Services) and mapped to
    ``http://<service-name>.<namespace>:<port>`` cluster-DNS URLs.
    """

    def __init__(
        self,
        namespace: str,
        project: Optional[str] = None,
        label_selector: Optional[str] = None,
        in_cluster: bool = True,
        scheme: str = "http",
    ):
        try:
            import kubernetes  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "KubeTargetDiscovery requires the 'kubernetes' client "
                "package, which is not installed in this environment. Pass "
                "explicit --targets to run-watchman instead."
            ) from exc
        from kubernetes import client, config

        if in_cluster:
            config.load_incluster_config()
        else:
            config.load_kube_config()
        self.namespace = namespace
        self.project = project
        self.label_selector = label_selector or (
            f"app.kubernetes.io/part-of=gordo,gordo/project={project}"
            if project
            else "app.kubernetes.io/part-of=gordo"
        )
        self.scheme = scheme
        self._core = client.CoreV1Api()
        #: live Service-name -> URL cache maintained by the watch thread;
        #: None means "not watching" and targets() falls back to listing
        self._watch_cache: Optional[Dict[str, str]] = None
        self._watch_lock = threading.Lock()
        #: per-GENERATION stop event: each start_watch() gets a fresh one,
        #: so an abandoned thread (join timed out while it idled inside a
        #: long watch stream) stays permanently stopped instead of being
        #: resurrected by the next start clearing a shared flag
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        #: thread-context callback fired when the watched target set
        #: changes (Watchman bridges it onto its event loop to refresh
        #: immediately instead of waiting out the poll interval)
        self.on_change: Optional[Callable[[], None]] = None

    def _svc_url(self, svc) -> str:
        ports = svc.spec.ports or []
        port = ports[0].port if ports else 80
        return f"{self.scheme}://{svc.metadata.name}.{self.namespace}:{port}"

    def _list_urls(self) -> Dict[str, str]:
        services = self._core.list_namespaced_service(
            self.namespace, label_selector=self.label_selector
        )
        return {svc.metadata.name: self._svc_url(svc) for svc in services.items}

    def targets(self) -> List[str]:
        """Current server base URLs — from the live watch cache when the
        watch thread is running, else one Service list call."""
        with self._watch_lock:
            if self._watch_cache is not None:
                return sorted(self._watch_cache.values())
        urls = sorted(self._list_urls().values())
        logger.debug(
            "k8s discovery (%s, %r): %d targets",
            self.namespace, self.label_selector, len(urls),
        )
        return urls

    # -- watch-based discovery ----------------------------------------------
    def start_watch(self) -> None:
        """Start the background Service watch (idempotent).

        The thread seeds the cache with a full list (resync), then applies
        ADDED/MODIFIED/DELETED events as they stream.  Any stream error
        drops the cache (``targets()`` falls back to listing), backs off,
        and re-syncs — the poll path is never worse than without watching.
        """
        if self._watch_thread is not None:
            return
        self._watch_stop = threading.Event()  # new generation, see __init__
        self._watch_thread = threading.Thread(
            target=self._watch_loop,
            args=(self._watch_stop,),
            name="gordo-kube-watch",
            daemon=True,
        )
        self._watch_thread.start()

    def stop_watch(self) -> None:
        self._watch_stop.set()
        thread, self._watch_thread = self._watch_thread, None
        if thread is not None:
            thread.join(timeout=5)
        with self._watch_lock:
            self._watch_cache = None

    def _notify(self) -> None:
        cb = self.on_change
        if cb is not None:
            try:
                cb()
            except Exception:
                logger.exception("Discovery on_change callback failed")

    def _watch_loop(self, stop: threading.Event) -> None:
        from kubernetes import watch

        backoff = 1.0
        while not stop.is_set():
            try:
                seeded = self._list_urls()
                with self._watch_lock:
                    if self._watch_stop is not stop:
                        # superseded generation (stop_watch join timed out
                        # while this thread idled in a list call, then a
                        # new start_watch began): its resync must not
                        # clobber the live generation's cache
                        return
                    changed = seeded != self._watch_cache
                    self._watch_cache = dict(seeded)
                if changed:
                    self._notify()
                w = watch.Watch()
                # bounded stream timeout: the loop re-lists (resync) after
                # each window, so a silently-dead stream self-heals
                for event in w.stream(
                    self._core.list_namespaced_service,
                    self.namespace,
                    label_selector=self.label_selector,
                    timeout_seconds=300,
                ):
                    if stop.is_set():
                        w.stop()
                        break
                    svc = event.get("object")
                    etype = event.get("type")
                    if svc is None or etype is None:
                        continue
                    name = svc.metadata.name
                    with self._watch_lock:
                        if self._watch_stop is not stop:
                            # a pending event from an abandoned generation
                            # races the new one's cache: drop it and die
                            return
                        if self._watch_cache is None:
                            self._watch_cache = {}
                        if etype == "DELETED":
                            changed = (
                                self._watch_cache.pop(name, None) is not None
                            )
                        else:  # ADDED / MODIFIED
                            url = self._svc_url(svc)
                            changed = self._watch_cache.get(name) != url
                            self._watch_cache[name] = url
                    if changed:
                        logger.info(
                            "k8s watch: %s %s", etype, name
                        )
                        self._notify()
                backoff = 1.0
            except Exception:
                logger.exception(
                    "Service watch stream failed; falling back to list "
                    "for %.0fs", backoff,
                )
                with self._watch_lock:
                    if self._watch_stop is not stop:
                        return  # never blank a successor's live cache
                    self._watch_cache = None  # poll path lists directly
                if stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 60.0)
