"""Kubernetes target discovery for watchman.

Reference equivalent: ``gordo_components/watchman`` watched kubernetes
namespace events to discover per-machine ml-server pods.  The TPU-era
topology is one server Deployment per project (many machines each), so
discovery here finds *server Services* by label and hands their URLs to
:class:`~gordo_tpu.watchman.server.Watchman` as targets; machine-level
discovery then rides each server's own project index
(``endpoints_status.discover_machines``).

Import-gated on the ``kubernetes`` client package (not in the TPU image);
tests fake the module in ``sys.modules`` — the reference mocked the k8s
client the same way (SURVEY.md §5 watchman bullet).
"""

from __future__ import annotations

import logging
from typing import List, Optional

logger = logging.getLogger(__name__)


class KubeTargetDiscovery:
    """Resolve ml-server base URLs from Services in a namespace.

    Services are selected by ``label_selector`` (default: the project
    label the workflow generator stamps on server Services) and mapped to
    ``http://<service-name>.<namespace>:<port>`` cluster-DNS URLs.
    """

    def __init__(
        self,
        namespace: str,
        project: Optional[str] = None,
        label_selector: Optional[str] = None,
        in_cluster: bool = True,
        scheme: str = "http",
    ):
        try:
            import kubernetes  # noqa: F401
        except ImportError as exc:
            raise ImportError(
                "KubeTargetDiscovery requires the 'kubernetes' client "
                "package, which is not installed in this environment. Pass "
                "explicit --targets to run-watchman instead."
            ) from exc
        from kubernetes import client, config

        if in_cluster:
            config.load_incluster_config()
        else:
            config.load_kube_config()
        self.namespace = namespace
        self.project = project
        self.label_selector = label_selector or (
            f"app.kubernetes.io/part-of=gordo,gordo/project={project}"
            if project
            else "app.kubernetes.io/part-of=gordo"
        )
        self.scheme = scheme
        self._core = client.CoreV1Api()

    def targets(self) -> List[str]:
        """Current server base URLs (one per matching Service)."""
        urls: List[str] = []
        services = self._core.list_namespaced_service(
            self.namespace, label_selector=self.label_selector
        )
        for svc in services.items:
            name = svc.metadata.name
            ports = svc.spec.ports or []
            port = ports[0].port if ports else 80
            urls.append(f"{self.scheme}://{name}.{self.namespace}:{port}")
        logger.debug(
            "k8s discovery (%s, %r): %d targets",
            self.namespace, self.label_selector, len(urls),
        )
        return urls
