"""Watchman — per-project fleet status service.

Reference equivalent: ``gordo_components/watchman/`` — a service that knows
the project's expected machine list and continuously polls every machine
endpoint's ``/healthcheck`` + ``/metadata``, aggregating into one
``GET /`` JSON status document consumed by dashboards and the client.
"""

from gordo_tpu.watchman.endpoints_status import (  # noqa: F401
    EndpointStatus,
    poll_endpoints,
)
from gordo_tpu.watchman.server import Watchman, build_watchman_app, run_watchman  # noqa: F401
