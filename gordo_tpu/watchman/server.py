"""Watchman HTTP service.

Reference equivalent: ``gordo_components/watchman/server.py`` — Flask app
whose ``GET /`` returns the aggregate project status JSON
(``{project-name, endpoints: [{endpoint, healthy, endpoint-metadata}]}``)
built by background polling threads.  Here: one aiohttp app with an
asyncio background poller (no thread pool needed), same response schema.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import Any, Dict, List, Optional, Sequence

from aiohttp import web

import gordo_tpu
from gordo_tpu import telemetry
from gordo_tpu.watchman.endpoints_status import (
    EndpointStatus,
    discover_machines_ex,
    fetch_fleet_health,
    poll_endpoints,
    scrape_metrics,
)

logger = logging.getLogger(__name__)

WATCHMAN_KEY: "web.AppKey[Watchman]" = web.AppKey("watchman", object)

_POLL_SECONDS = telemetry.histogram(
    "gordo_watchman_poll_seconds",
    "Duration of one full endpoint poll cycle",
)
_ENDPOINTS_GAUGE = telemetry.gauge(
    "gordo_watchman_endpoints",
    "Endpoints by health as of the latest poll",
    labels=("healthy",),
)
_TARGET_SHARD_GAUGE = telemetry.gauge(
    "gordo_watchman_target_shard_index",
    "Each target replica's serving shard index (routing topology; only "
    "sharded targets report one)",
    labels=("target",),
)
_TARGET_GENERATION_GAUGE = telemetry.gauge(
    "gordo_watchman_target_fleet_generation",
    "Each target replica's fleet-generation stamp — diverging values "
    "across a sharded tier mean a rollout is mid-propagation",
    labels=("target",),
)
_TARGETS_DOWN_GAUGE = telemetry.gauge(
    "gordo_watchman_targets_down",
    "Target replicas currently marked down (failed "
    "GORDO_WATCHMAN_EVICT_AFTER consecutive index scrapes)",
)

#: a target failing this many CONSECUTIVE index scrapes is marked
#: ``down`` in the status doc's ``targets`` section — clients skip it
#: during shard-table bootstrap and as a failover candidate
ENV_EVICT_AFTER = "GORDO_WATCHMAN_EVICT_AFTER"


class Watchman:
    """Holds the latest per-endpoint statuses, refreshed by a background
    task every ``poll_interval`` seconds."""

    def __init__(
        self,
        project: str,
        machines: Sequence[str],
        target_base_urls: Sequence[str],
        poll_interval: float = 30.0,
        request_timeout: float = 5.0,
        namespace: Optional[str] = None,
        discover: bool = True,
        target_discovery: Optional[Any] = None,
        evict_after: int = 3,
    ):
        self.project = project
        self.machines = list(machines)
        #: statically configured machines are never evicted — only machines
        #: that ARRIVED via discovery can LEAVE via discovery
        self._configured = set(self.machines)
        #: machines evict after this many consecutive polls in which EVERY
        #: target index responded and none listed the machine
        #: (reference parity: a deleted deployment disappears from watchman
        #: once its pod is gone, instead of being reported unhealthy forever)
        self.evict_after = evict_after
        self._discovery_misses: Dict[str, int] = {}
        #: targets mark ``down`` after this many consecutive failed index
        #: scrapes (env ``GORDO_WATCHMAN_EVICT_AFTER`` overrides; default
        #: matches the machine-eviction threshold)
        try:
            self.target_evict_after = max(
                1, int(os.environ.get(ENV_EVICT_AFTER, evict_after))
            )
        except ValueError:
            self.target_evict_after = evict_after
        self._target_failures: Dict[str, int] = {}
        self._last_targets: List[str] = list(target_base_urls)
        self.target_base_urls = list(target_base_urls)
        self.poll_interval = poll_interval
        self.request_timeout = request_timeout
        self.namespace = namespace
        #: also ask each target's project index for its machine list, so
        #: machines appearing after startup are polled without reconfig
        #: (reference parity: the k8s-event endpoint discovery)
        self.discover = discover
        #: optional ``watchman.kube.KubeTargetDiscovery``-shaped object
        #: contributing target base urls (``.targets() -> [url]``)
        self.target_discovery = target_discovery
        self.started_at = time.time()
        self.statuses: Dict[str, EndpointStatus] = {}
        #: per-target artifact format from the latest discovery poll
        #: ({base_url: "v2-packs" | "v1-dirs"}) — republished in the
        #: status document so a rollout to packed artifacts is visible
        #: fleet-wide without querying every server
        self.artifact_formats: Dict[str, str] = {}
        #: per-target routing topology from the latest discovery poll
        #: ({base_url: {shard-index, shard-count, fleet-generation,
        #: machines}}) — republished in the status document AND as
        #: per-target gauges on /metrics, so shard layout and rollout
        #: generation are readable from ONE endpoint
        self.serve_topology: Dict[str, Dict[str, Any]] = {}
        #: per-target last scrape error ({base_url: message}) from the
        #: most recent /metrics fan-out — a target that stops answering
        #: its scrape is now visible in the status doc, not just as a
        #: silently-thinner merged exposition
        self.scrape_errors: Dict[str, str] = {}
        self._task: Optional[asyncio.Task] = None
        self._loop_ref: Optional[asyncio.AbstractEventLoop] = None
        self._wake: Optional[asyncio.Event] = None

    async def _current_targets(self) -> List[str]:
        targets = list(self.target_base_urls)
        if self.target_discovery is not None:
            try:
                loop = asyncio.get_running_loop()
                discovered = await loop.run_in_executor(
                    None, self.target_discovery.targets
                )
                for url in discovered:
                    if url not in targets:
                        targets.append(url)
            except Exception:
                logger.exception("Target discovery failed")
        return targets

    async def refresh(self) -> List[EndpointStatus]:
        t0 = time.monotonic()
        targets = await self._current_targets()
        self._last_targets = targets
        if self.discover:
            formats: Dict[str, str] = {}
            topology: Dict[str, Dict[str, Any]] = {}
            discovered, n_responding = await discover_machines_ex(
                self.project, targets, timeout=self.request_timeout,
                artifact_formats=formats, topology=topology,
            )
            if formats:
                self.artifact_formats = formats
            if topology:
                self.serve_topology = topology
                for base, entry in topology.items():
                    if "shard-index" in entry:
                        _TARGET_SHARD_GAUGE.set(
                            float(entry["shard-index"]), base
                        )
                    if "fleet-generation" in entry:
                        _TARGET_GENERATION_GAUGE.set(
                            float(entry["fleet-generation"]), base
                        )
            # per-target down-marking: ``topology`` gains an entry for
            # every target whose index answered this cycle, so absence
            # IS a failed scrape.  ``target_evict_after`` consecutive
            # misses flip the target ``down`` in the status doc (clients
            # then skip it when bootstrapping their shard table and when
            # picking failover candidates); one successful scrape clears
            # the counter.
            responded = set(topology)
            for base in targets:
                if base in responded:
                    was = self._target_failures.pop(base, 0)
                    if was >= self.target_evict_after:
                        logger.info(
                            "Target %s recovered after %d failed scrapes",
                            base, was,
                        )
                    continue
                n_fail = self._target_failures.get(base, 0) + 1
                self._target_failures[base] = n_fail
                if n_fail == self.target_evict_after:
                    logger.warning(
                        "Marking target %s down: %d consecutive failed "
                        "index scrapes", base, n_fail,
                    )
            _TARGETS_DOWN_GAUGE.set(float(len(self.targets_down)))
            for name in discovered:
                if name not in self.machines:
                    self.machines.append(name)
            if n_responding == len(targets) and targets:
                # EVERY target's index responded and omitted these machines;
                # count a miss.  A partial or total outage counts nothing —
                # a machine hosted only on the one server that is down must
                # surface as unhealthy, not silently evict because the
                # other servers' indexes (which never listed it) answered.
                present = set(discovered)
                for name in list(self.machines):
                    if name in self._configured or name in present:
                        self._discovery_misses.pop(name, None)
                        continue
                    misses = self._discovery_misses.get(name, 0) + 1
                    if misses >= self.evict_after:
                        logger.info(
                            "Evicting machine %r: absent from every "
                            "responding index for %d polls", name, misses,
                        )
                        self.machines.remove(name)
                        self.statuses.pop(name, None)
                        self._discovery_misses.pop(name, None)
                    else:
                        self._discovery_misses[name] = misses
        machines = list(self.machines)
        statuses = await poll_endpoints(
            self.project,
            machines,
            targets,
            timeout=self.request_timeout,
        )
        for status in statuses:
            prev = self.statuses.get(status.machine)
            if not status.healthy and prev is not None:
                status.last_seen = prev.last_seen
            self.statuses[status.machine] = status
        _POLL_SECONDS.observe(time.monotonic() - t0)
        n_healthy = sum(1 for s in statuses if s.healthy)
        _ENDPOINTS_GAUGE.set(n_healthy, "true")
        _ENDPOINTS_GAUGE.set(len(statuses) - n_healthy, "false")
        return statuses

    @property
    def targets_down(self) -> set:
        """Target base urls currently past the consecutive-scrape-failure
        threshold."""
        return {
            base for base, n in self._target_failures.items()
            if n >= self.target_evict_after
        }

    def notify_change(self) -> None:
        """Thread-safe nudge: refresh on the next loop tick instead of
        waiting out ``poll_interval`` (wired to watch-based discovery's
        ``on_change`` so fleet membership changes propagate at event
        latency)."""
        loop, event = self._loop_ref, self._wake
        if loop is not None and event is not None:
            try:
                loop.call_soon_threadsafe(event.set)
            except RuntimeError:
                pass  # loop already closed

    async def _loop(self) -> None:
        self._loop_ref = asyncio.get_running_loop()
        self._wake = asyncio.Event()
        while True:
            try:
                await self.refresh()
            except Exception:
                logger.exception("Watchman poll cycle failed")
            try:
                await asyncio.wait_for(
                    self._wake.wait(), timeout=self.poll_interval
                )
                self._wake.clear()
            except asyncio.TimeoutError:
                pass  # normal poll-cadence tick

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._loop())
            # watch-capable discovery: stream events and nudge the loop
            disc = self.target_discovery
            if disc is not None and hasattr(disc, "start_watch"):
                disc.on_change = self.notify_change
                try:
                    disc.start_watch()
                except Exception:
                    logger.exception(
                        "Watch-based discovery failed to start; polling only"
                    )

    async def stop(self) -> None:
        disc = self.target_discovery
        if disc is not None and hasattr(disc, "stop_watch"):
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, disc.stop_watch
                )
            except Exception:
                logger.exception("Stopping watch-based discovery failed")
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    def to_json(self) -> Dict:
        return {
            "project-name": self.project,
            "gordo-server-version": gordo_tpu.__version__,
            "uptime-seconds": round(time.time() - self.started_at, 1),
            "target-base-urls": self.target_base_urls,
            "artifact-formats": dict(self.artifact_formats),
            # routing topology: each target's shard identity, fleet
            # generation and served machines (empty entries for targets
            # that never answered their index)
            "serve-topology": {
                base: dict(entry)
                for base, entry in self.serve_topology.items()
            },
            # per-target scrape health: last /metrics fan-out error per
            # target (absent entry = last scrape succeeded); counts live
            # in gordo_watchman_scrape_failures_total{instance=...}
            "scrape-status": {
                base: {"last-error": err}
                for base, err in sorted(self.scrape_errors.items())
            },
            # per-target liveness: a target past the consecutive
            # index-scrape-failure threshold is ``down`` — clients skip
            # it when bootstrapping their shard table from serve-topology
            # and when picking failover candidates
            "targets": {
                base: {
                    "down": self._target_failures.get(base, 0)
                    >= self.target_evict_after,
                    "consecutive-scrape-failures":
                        self._target_failures.get(base, 0),
                }
                for base in sorted(
                    set(self._last_targets) | set(self._target_failures)
                )
            },
            "endpoints": [
                self.statuses[m].to_json()
                for m in self.machines
                if m in self.statuses
            ],
        }


async def _index(request: web.Request) -> web.Response:
    watchman: Watchman = request.app[WATCHMAN_KEY]
    if not watchman.statuses:  # first request before the poller has run
        await watchman.refresh()
    return web.json_response(watchman.to_json())


async def _healthcheck(request: web.Request) -> web.Response:
    return web.json_response({"gordo-server-version": gordo_tpu.__version__})


async def _metrics(request: web.Request) -> web.Response:
    """The FLEET scrape surface: every target server's ``/metrics`` merged
    under per-target ``instance`` labels, plus watchman's own series
    (``instance="watchman"``).  One scrape config covers the whole
    project — Prometheus points here instead of at N server pods."""
    watchman: Watchman = request.app[WATCHMAN_KEY]
    targets = await watchman._current_targets()
    merged, n_responding = await scrape_metrics(
        targets,
        timeout=watchman.request_timeout,
        extra=[("watchman", telemetry.render())],
        errors=watchman.scrape_errors,
    )
    resp = web.Response(text=merged, content_type="text/plain")
    resp.headers["X-Gordo-Scraped-Targets"] = str(n_responding)
    return resp


async def _fleet_health(request: web.Request) -> web.Response:
    """The FLEET health surface: every target replica's per-machine
    fleet-health doc fetched and merged into one view.  Sketches merge
    exactly (counts add), so for a machine-affinity-sharded tier this
    doc is the same as a single process serving the whole fleet would
    produce — one endpoint answers "which of my machines are drifting"
    regardless of how serving is sharded.  ``?top=N`` bounds the drift
    ranking."""
    watchman: Watchman = request.app[WATCHMAN_KEY]
    try:
        top = int(request.query.get("top")) if "top" in request.query else None
    except (TypeError, ValueError):
        return web.json_response(
            {"error": "top must be an integer"}, status=400
        )
    targets = await watchman._current_targets()
    docs, responding = await fetch_fleet_health(
        watchman.project, targets,
        timeout=watchman.request_timeout, top=top,
    )
    merged = telemetry.merge_health_docs(docs, top=top)
    merged["project-name"] = watchman.project
    merged["instances"] = responding
    merged["targets-responding"] = len(responding)
    return web.json_response(merged)


class StreamRelay:
    """Re-fan the fleet's streams as ONE merged alert surface.

    Watchman subscribes to every target replica's ``/stream`` (lazily —
    the upstream SSE connections start on the first local subscriber)
    and republishes the events through its own relay hub
    (``StreamHub(collection=None)``), so a consumer watching the whole
    sharded fleet holds one connection HERE instead of one per replica.
    Relay events keep the upstream payload and gain ``target`` (which
    replica) and ``origin-id`` (the upstream event id); the ``id`` the
    relay stamps is its own — ``Last-Event-ID`` resume against watchman
    works the same as against a replica, while each upstream connection
    resumes independently with its per-target cursor, so a replica
    bounce loses nothing its ring still holds."""

    def __init__(self, watchman: Watchman):
        from gordo_tpu.serve import stream as stream_mod

        self.watchman = watchman
        self.hub = stream_mod.StreamHub()
        self._tasks: Dict[str, asyncio.Task] = {}
        self._cursors: Dict[str, int] = {}
        self._session: Optional[Any] = None

    async def ensure_started(self) -> None:
        """(Re)start one upstream pump per current target."""
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession()
        loop = asyncio.get_running_loop()
        for base in await self.watchman._current_targets():
            task = self._tasks.get(base)
            if task is None or task.done():
                self._tasks[base] = loop.create_task(self._pump(base))

    async def _pump(self, base: str) -> None:
        from gordo_tpu.client.io import sse_events

        url = f"{base}/gordo/v0/{self.watchman.project}/stream"
        while True:
            try:
                async for ev in sse_events(
                    self._session, url,
                    last_event_id=self._cursors.get(base),
                ):
                    self._cursors[base] = ev["id"]
                    data = dict(ev["data"])
                    data["target"] = base
                    data["origin-id"] = ev["id"]
                    self.hub.publish(ev["type"], data)
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                # sse_events already burned its reconnect budget — the
                # target is properly down; keep trying at poll cadence so
                # the relay heals itself when the replica comes back
                logger.warning("Stream relay to %s failed: %s", base, exc)
                await asyncio.sleep(
                    min(self.watchman.poll_interval, 10.0) or 5.0
                )

    async def close(self) -> None:
        for task in self._tasks.values():
            task.cancel()
        for task in self._tasks.values():
            try:
                await task
            except (asyncio.CancelledError, Exception):
                pass
        self._tasks.clear()
        if self._session is not None and not self._session.closed:
            await self._session.close()


STREAM_RELAY_KEY: "web.AppKey[StreamRelay]" = web.AppKey(
    "stream_relay", object
)


async def _stream(request: web.Request) -> web.StreamResponse:
    """``GET /stream``: the merged fleet alert stream (see
    :class:`StreamRelay`).  Same wire contract as a replica's stream
    route — SSE by default with ``Last-Event-ID`` resume,
    ``?mode=poll&after=N`` long-poll fallback, ``?machines=a,b``
    filter — but machine names here are NOT validated against a shard
    table: the relay fans in from every target, so any filter is just a
    filter."""
    from gordo_tpu.serve import stream as stream_mod

    relay: StreamRelay = request.app[STREAM_RELAY_KEY]
    await relay.ensure_started()
    hub = relay.hub
    machines = None
    if request.query.get("machines"):
        machines = {
            m for m in request.query["machines"].split(",") if m
        }
    raw = request.headers.get("Last-Event-ID") or request.query.get("after")
    try:
        after = int(raw) if raw is not None else hub.ring.last_id
    except ValueError:
        return web.json_response(
            {"error": f"bad event id {raw!r}"}, status=400
        )

    if request.query.get("mode") == "poll":
        try:
            timeout = min(
                float(request.query.get("timeout", "1e9")),
                stream_mod.poll_timeout_seconds(),
            )
        except ValueError:
            timeout = stream_mod.poll_timeout_seconds()
        doc = await stream_mod.poll_events(hub, machines, after, timeout)
        return web.json_response(doc)

    sub = hub.subscribe(machines)
    response = web.StreamResponse(
        status=200,
        headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "X-Accel-Buffering": "no",
        },
    )
    response.enable_chunked_encoding()
    await response.prepare(request)
    try:
        await stream_mod.run_sse(response, hub, sub, after)
    except (ConnectionResetError, ConnectionError, asyncio.CancelledError):
        pass  # peer went away — run_sse unsubscribed
    return response


def build_watchman_app(watchman: Watchman) -> web.Application:
    app = web.Application()
    app[WATCHMAN_KEY] = watchman
    app[STREAM_RELAY_KEY] = StreamRelay(watchman)

    async def _start(app):
        watchman.start()

    async def _stop(app):
        await app[STREAM_RELAY_KEY].close()
        await watchman.stop()

    app.on_startup.append(_start)
    app.on_cleanup.append(_stop)
    app.router.add_get("/", _index)
    app.router.add_get("/healthcheck", _healthcheck)
    app.router.add_get("/metrics", _metrics)
    app.router.add_get("/fleet-health", _fleet_health)
    app.router.add_get("/stream", _stream)
    return app


def run_watchman(
    project: str,
    machines: Sequence[str],
    target_base_urls: Sequence[str],
    host: str = "0.0.0.0",
    port: int = 5556,
    poll_interval: float = 30.0,
    discover: bool = True,
    target_discovery: Optional[Any] = None,
) -> None:
    """Blocking entrypoint (reference: ``gordo run-watchman``)."""
    watchman = Watchman(
        project, machines, target_base_urls, poll_interval=poll_interval,
        discover=discover, target_discovery=target_discovery,
    )
    logger.info(
        "Watchman for project %r: %d machines, %d targets, every %.0fs",
        project, len(machines), len(target_base_urls), poll_interval,
    )
    web.run_app(build_watchman_app(watchman), host=host, port=port)
