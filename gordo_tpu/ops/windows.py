"""Device-side sliding-window materialisation for LSTM estimators.

Reference equivalent: the keras ``TimeseriesGenerator`` helper used by
``KerasLSTMAutoEncoder``/``KerasLSTMForecast`` in
``gordo_components/model/models.py`` — there a host-side Python generator;
here a single gather on device (static shapes, vmap/jit-safe).
"""

from __future__ import annotations

import jax.numpy as jnp


def num_windows(n_rows: int, lookback: int) -> int:
    return max(n_rows - lookback + 1, 0)


def make_windows(X: jnp.ndarray, lookback: int) -> jnp.ndarray:
    """(N, F) -> (N - lookback + 1, lookback, F) overlapping windows."""
    X = jnp.asarray(X)
    n = X.shape[0]
    if n < lookback:
        raise ValueError(f"Need at least lookback={lookback} rows, got {n}")
    idx = jnp.arange(n - lookback + 1)[:, None] + jnp.arange(lookback)[None, :]
    return X[idx]
