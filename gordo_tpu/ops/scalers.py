"""Functional, jit-foldable preprocessing transforms.

Reference equivalents: the sklearn transformers gordo-components composes in
its pipelines (``sklearn.preprocessing.MinMaxScaler`` etc. — aliased onto
these classes by :data:`gordo_tpu.registry.ALIASES`) plus
``gordo_components/model/transformers/``.

TPU-native design: a transform is *stats + a pure function*.  ``fit``
computes stats on device (one fused XLA reduction, NaN-aware); ``transform``
/ ``inverse_transform`` are pure jnp functions of ``(stats, X)`` so
estimators and the anomaly scorer can fold them into a single jitted program
instead of round-tripping through host numpy between pipeline steps
(the sklearn execution model the reference inherits).
"""

from __future__ import annotations

import warnings
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gordo_tpu.utils.args import ParamsMixin, capture_args

_EPS = 1e-12


def _warn_ignored(cls_name: str, kwargs: dict) -> None:
    """Sklearn-compat kwargs this implementation does not honour (e.g.
    ``QuantileTransformer(subsample=...)``, ``PCA(whiten=True)``,
    ``SimpleImputer(add_indicator=True)``) are accepted so reference YAML
    loads unchanged — but silently changing behaviour is worse than a
    loud warning, so say exactly what is being ignored."""
    if kwargs:
        warnings.warn(
            f"{cls_name}: ignoring unsupported sklearn kwargs "
            f"{sorted(kwargs)} — behaviour may differ from sklearn",
            UserWarning,
            stacklevel=3,
        )


def as_float2d(X) -> jnp.ndarray:
    """Coerce input to a float32 2-D jnp array (shared shape/dtype policy)."""
    X = jnp.asarray(X, dtype=jnp.float32)
    if X.ndim == 1:
        X = X[:, None]
    return X


_as2d = as_float2d


class BaseTransform(ParamsMixin):
    """Stats + pure-function transform. Subclasses define the static fns."""

    def __init__(self):
        self.stats_: Optional[dict] = None

    # -- pure functions (jit-safe, also used folded into estimator programs).
    # CONTRACT: ``stats`` is self-contained — every constructor option that
    # affects the transform is folded into the stats at fit time, so
    # ``apply(stats, X)`` inside a jitted program always agrees with the
    # stateful ``transform(X)``.
    @staticmethod
    def compute_stats(X: jnp.ndarray, **options) -> dict:  # pragma: no cover
        raise NotImplementedError

    @staticmethod
    def apply(stats: dict, X: jnp.ndarray) -> jnp.ndarray:  # pragma: no cover
        raise NotImplementedError

    @staticmethod
    def invert(stats: dict, X: jnp.ndarray) -> jnp.ndarray:
        raise NotImplementedError("transform is not invertible")

    def _stat_options(self) -> dict:
        """Constructor options forwarded to ``compute_stats`` at fit time."""
        return {}

    # -- sklearn-flavoured stateful API -------------------------------------
    def fit(self, X, y=None):
        from gordo_tpu.utils.trees import to_host

        self.stats_ = to_host(
            type(self).compute_stats(_as2d(X), **self._stat_options())
        )
        return self

    def fit_transform(self, X, y=None):
        return self.fit(X, y).transform(X)

    def transform(self, X):
        self._check_fitted()
        return np.asarray(type(self).apply(self.stats_, _as2d(X)))

    def inverse_transform(self, X):
        self._check_fitted()
        try:
            return np.asarray(type(self).invert(self.stats_, _as2d(X)))
        except NotImplementedError:
            raise NotImplementedError(
                f"{type(self).__name__} is not invertible"
            ) from None

    def _check_fitted(self):
        if self.stats_ is None:
            raise RuntimeError(f"{type(self).__name__} is not fitted")

    def __getstate__(self):
        from gordo_tpu.utils.trees import to_host

        state = dict(self.__dict__)
        state["stats_"] = to_host(state.get("stats_"))
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)


class MinMaxScaler(BaseTransform):
    """Scale features to ``feature_range`` (default [0, 1]).

    Stats are a folded affine map (``scale``/``offset``) so the pure
    ``apply`` honours the configured range."""

    @capture_args
    def __init__(self, feature_range=(0, 1), **_sklearn_kwargs):
        super().__init__()
        _warn_ignored(type(self).__name__, _sklearn_kwargs)
        self.feature_range = tuple(feature_range)

    def _stat_options(self):
        return {"feature_range": self.feature_range}

    @staticmethod
    def compute_stats(X, feature_range=(0.0, 1.0)):
        a, b = feature_range
        lo = jnp.nanmin(X, axis=0)
        hi = jnp.nanmax(X, axis=0)
        scale = (b - a) / jnp.maximum(hi - lo, _EPS)
        return {"scale": scale, "offset": a - lo * scale}

    @staticmethod
    def apply(stats, X):
        return X * stats["scale"] + stats["offset"]

    @staticmethod
    def invert(stats, X):
        return (X - stats["offset"]) / stats["scale"]


class StandardScaler(BaseTransform):
    """Zero-mean unit-variance per feature."""

    @capture_args
    def __init__(self, with_mean: bool = True, with_std: bool = True, **_sklearn_kwargs):
        super().__init__()
        _warn_ignored(type(self).__name__, _sklearn_kwargs)
        self.with_mean = with_mean
        self.with_std = with_std

    def _stat_options(self):
        return {"with_mean": self.with_mean, "with_std": self.with_std}

    @staticmethod
    def compute_stats(X, with_mean=True, with_std=True):
        mean = jnp.nanmean(X, axis=0)
        std = jnp.maximum(jnp.nanstd(X, axis=0), _EPS)
        ones = jnp.ones_like(std)
        return {
            "mean": mean if with_mean else jnp.zeros_like(mean),
            "std": std if with_std else ones,
        }

    @staticmethod
    def apply(stats, X):
        return (X - stats["mean"]) / stats["std"]

    @staticmethod
    def invert(stats, X):
        return X * stats["std"] + stats["mean"]


class RobustScaler(BaseTransform):
    """Median/IQR scaling (outlier-robust, the detector's usual scaler)."""

    @capture_args
    def __init__(self, with_centering: bool = True, with_scaling: bool = True,
                 quantile_range=(25.0, 75.0), **_sklearn_kwargs):
        super().__init__()
        _warn_ignored(type(self).__name__, _sklearn_kwargs)
        self.with_centering = with_centering
        self.with_scaling = with_scaling
        self.quantile_range = tuple(quantile_range)

    def _stat_options(self):
        return {
            "with_centering": self.with_centering,
            "with_scaling": self.with_scaling,
            "quantile_range": self.quantile_range,
        }

    @staticmethod
    def compute_stats(X, with_centering=True, with_scaling=True,
                      quantile_range=(25.0, 75.0)):
        lo, hi = quantile_range
        q = jnp.nanpercentile(X, jnp.array([lo, 50.0, hi]), axis=0)
        center = q[1]
        scale = jnp.maximum(q[2] - q[0], _EPS)
        return {
            "center": center if with_centering else jnp.zeros_like(center),
            "scale": scale if with_scaling else jnp.ones_like(scale),
        }

    @staticmethod
    def apply(stats, X):
        return (X - stats["center"]) / stats["scale"]

    @staticmethod
    def invert(stats, X):
        return X * stats["scale"] + stats["center"]


class QuantileTransformer(BaseTransform):
    """Map features onto a uniform (or normal) distribution via per-feature
    quantile grids + linear interpolation.  Stats are a fixed-size grid so the
    transform stays jit-friendly (static shapes)."""

    @capture_args
    def __init__(self, n_quantiles: int = 100, output_distribution: str = "uniform",
                 **_sklearn_kwargs):
        super().__init__()
        _warn_ignored(type(self).__name__, _sklearn_kwargs)
        self.n_quantiles = int(n_quantiles)
        self.output_distribution = output_distribution

    def fit(self, X, y=None):
        from gordo_tpu.utils.trees import to_host

        X = _as2d(X)
        qs = jnp.linspace(0.0, 100.0, self.n_quantiles)
        self.stats_ = to_host({"grid": jnp.nanpercentile(X, qs, axis=0)})
        return self

    def transform(self, X):
        self._check_fitted()
        X = _as2d(X)
        grid = jnp.asarray(self.stats_["grid"])  # (Q, F)
        qs = jnp.linspace(0.0, 1.0, grid.shape[0])
        out = jax.vmap(
            lambda col, g: jnp.interp(col, g, qs), in_axes=(1, 1), out_axes=1
        )(X, grid)
        if self.output_distribution == "normal":
            from jax.scipy.stats import norm

            out = norm.ppf(jnp.clip(out, 1e-6, 1 - 1e-6))
        return np.asarray(out)

    def inverse_transform(self, X):
        self._check_fitted()
        X = _as2d(X)
        if self.output_distribution == "normal":
            from jax.scipy.stats import norm

            X = norm.cdf(X)
        grid = jnp.asarray(self.stats_["grid"])
        qs = jnp.linspace(0.0, 1.0, grid.shape[0])
        out = jax.vmap(
            lambda col, g: jnp.interp(col, qs, g), in_axes=(1, 1), out_axes=1
        )(X, grid)
        return np.asarray(out)


class SimpleImputer(BaseTransform):
    """Fill NaNs with a per-feature statistic (mean/median/constant)."""

    @capture_args
    def __init__(self, strategy: str = "mean", fill_value: float = 0.0,
                 **_sklearn_kwargs):
        super().__init__()
        _warn_ignored(type(self).__name__, _sklearn_kwargs)
        self.strategy = strategy
        self.fill_value = fill_value

    def fit(self, X, y=None):
        from gordo_tpu.utils.trees import to_host

        X = _as2d(X)
        if self.strategy == "mean":
            fill = jnp.nanmean(X, axis=0)
        elif self.strategy == "median":
            fill = jnp.nanmedian(X, axis=0)
        elif self.strategy == "constant":
            fill = jnp.full((X.shape[1],), float(self.fill_value))
        else:
            raise ValueError(f"Unknown imputer strategy {self.strategy!r}")
        self.stats_ = to_host({"fill": fill})
        return self

    @staticmethod
    def apply(stats, X):
        return jnp.where(jnp.isnan(X), stats["fill"], X)

    @staticmethod
    def invert(stats, X):
        return X

    def transform(self, X):
        self._check_fitted()
        return np.asarray(SimpleImputer.apply(self.stats_, _as2d(X)))

    def inverse_transform(self, X):
        return np.asarray(_as2d(X))


class PCA(BaseTransform):
    """Principal component projection via on-device SVD."""

    @capture_args
    def __init__(self, n_components: Optional[int] = None, **_sklearn_kwargs):
        super().__init__()
        _warn_ignored(type(self).__name__, _sklearn_kwargs)
        self.n_components = n_components

    def fit(self, X, y=None):
        from gordo_tpu.utils.trees import to_host

        X = _as2d(X)
        k = self.n_components or X.shape[1]
        mean = jnp.mean(X, axis=0)
        _, _, vt = jnp.linalg.svd(X - mean, full_matrices=False)
        self.stats_ = to_host({"mean": mean, "components": vt[:k]})
        return self

    @staticmethod
    def apply(stats, X):
        return (X - stats["mean"]) @ stats["components"].T

    @staticmethod
    def invert(stats, X):
        return X @ stats["components"] + stats["mean"]

    def transform(self, X):
        self._check_fitted()
        return np.asarray(PCA.apply(self.stats_, _as2d(X)))

    def inverse_transform(self, X):
        self._check_fitted()
        return np.asarray(PCA.invert(self.stats_, _as2d(X)))


class FunctionTransformer(BaseTransform):
    """Apply an arbitrary (registered) callable as a pipeline step.

    Reference: ``sklearn.preprocessing.FunctionTransformer`` carrying funcs
    from ``gordo_components/model/transformer_funcs/general.py``.
    """

    @capture_args
    def __init__(self, func: Optional[Callable] = None,
                 inverse_func: Optional[Callable] = None, kw_args: Optional[dict] = None,
                 inv_kw_args: Optional[dict] = None):
        super().__init__()
        self.func = func
        self.inverse_func = inverse_func
        self.kw_args = kw_args or {}
        self.inv_kw_args = inv_kw_args or {}

    def fit(self, X, y=None):
        self.stats_ = {}
        return self

    def transform(self, X):
        if self.func is None:
            return np.asarray(_as2d(X))
        return np.asarray(self.func(_as2d(X), **self.kw_args))

    def inverse_transform(self, X):
        if self.inverse_func is None:
            return np.asarray(_as2d(X))
        return np.asarray(self.inverse_func(_as2d(X), **self.inv_kw_args))

    def get_params(self, deep: bool = False):
        params = dict(self._init_params)
        # store funcs as dotted paths for definition round-trip
        for key in ("func", "inverse_func"):
            fn = params.get(key)
            if callable(fn):
                params[key] = f"{fn.__module__}.{fn.__qualname__}"
        return params
