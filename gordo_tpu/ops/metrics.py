"""Pure-jax regression metrics.

Reference equivalents: the sklearn metrics used in
``gordo_components/builder/build_model.py`` cross-validation
(explained variance, r2, MAE, MSE) — here as jit/vmap-safe jnp functions so
CV scoring runs on device, including vmapped across folds and models.
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-12


def _flatten_targets(y_true, y_pred):
    y_true = jnp.asarray(y_true, dtype=jnp.float32)
    y_pred = jnp.asarray(y_pred, dtype=jnp.float32)
    if y_true.ndim == 1:
        y_true = y_true[:, None]
    if y_pred.ndim == 1:
        y_pred = y_pred[:, None]
    return y_true, y_pred


def explained_variance_score(y_true, y_pred, sample_weight=None) -> jnp.ndarray:
    """Variance-weighted explained variance (sklearn semantics,
    ``multioutput='uniform_average'``)."""
    y_true, y_pred = _flatten_targets(y_true, y_pred)
    diff = y_true - y_pred
    num = jnp.var(diff - jnp.mean(diff, axis=0), axis=0)
    den = jnp.var(y_true - jnp.mean(y_true, axis=0), axis=0)
    per_output = 1.0 - num / jnp.maximum(den, _EPS)
    return jnp.mean(per_output)


def r2_score(y_true, y_pred) -> jnp.ndarray:
    y_true, y_pred = _flatten_targets(y_true, y_pred)
    ss_res = jnp.sum((y_true - y_pred) ** 2, axis=0)
    ss_tot = jnp.sum((y_true - jnp.mean(y_true, axis=0)) ** 2, axis=0)
    return jnp.mean(1.0 - ss_res / jnp.maximum(ss_tot, _EPS))


def mean_squared_error(y_true, y_pred) -> jnp.ndarray:
    y_true, y_pred = _flatten_targets(y_true, y_pred)
    return jnp.mean((y_true - y_pred) ** 2)


def mean_absolute_error(y_true, y_pred) -> jnp.ndarray:
    y_true, y_pred = _flatten_targets(y_true, y_pred)
    return jnp.mean(jnp.abs(y_true - y_pred))


METRICS = {
    "explained_variance_score": explained_variance_score,
    "r2_score": r2_score,
    "mean_squared_error": mean_squared_error,
    "mean_absolute_error": mean_absolute_error,
}


# ---------------------------------------------------------------------------
# Row-weighted variants (pad-up fleet mode: zero-weight padded rows).
# With all-ones weights each reduces to its unweighted counterpart.
# ---------------------------------------------------------------------------

def _wmean(a: jnp.ndarray, w: jnp.ndarray) -> jnp.ndarray:
    """Weighted column mean of ``a`` (n, F) with row weights ``w`` (n,)."""
    return jnp.sum(a * w[:, None], axis=0) / jnp.maximum(jnp.sum(w), _EPS)


def weighted_explained_variance_score(y_true, y_pred, w) -> jnp.ndarray:
    y_true, y_pred = _flatten_targets(y_true, y_pred)
    diff = y_true - y_pred
    num = _wmean((diff - _wmean(diff, w)) ** 2, w)
    den = _wmean((y_true - _wmean(y_true, w)) ** 2, w)
    return jnp.mean(1.0 - num / jnp.maximum(den, _EPS))


def weighted_r2_score(y_true, y_pred, w) -> jnp.ndarray:
    y_true, y_pred = _flatten_targets(y_true, y_pred)
    ss_res = jnp.sum(w[:, None] * (y_true - y_pred) ** 2, axis=0)
    ss_tot = jnp.sum(
        w[:, None] * (y_true - _wmean(y_true, w)) ** 2, axis=0
    )
    return jnp.mean(1.0 - ss_res / jnp.maximum(ss_tot, _EPS))


def weighted_mean_squared_error(y_true, y_pred, w) -> jnp.ndarray:
    y_true, y_pred = _flatten_targets(y_true, y_pred)
    return jnp.mean(_wmean((y_true - y_pred) ** 2, w))


def weighted_mean_absolute_error(y_true, y_pred, w) -> jnp.ndarray:
    y_true, y_pred = _flatten_targets(y_true, y_pred)
    return jnp.mean(_wmean(jnp.abs(y_true - y_pred), w))


WEIGHTED_METRICS = {
    "explained_variance_score": weighted_explained_variance_score,
    "r2_score": weighted_r2_score,
    "mean_squared_error": weighted_mean_squared_error,
    "mean_absolute_error": weighted_mean_absolute_error,
}
