"""Pure-jax regression metrics.

Reference equivalents: the sklearn metrics used in
``gordo_components/builder/build_model.py`` cross-validation
(explained variance, r2, MAE, MSE) — here as jit/vmap-safe jnp functions so
CV scoring runs on device, including vmapped across folds and models.
"""

from __future__ import annotations

import jax.numpy as jnp

_EPS = 1e-12


def _flatten_targets(y_true, y_pred):
    y_true = jnp.asarray(y_true, dtype=jnp.float32)
    y_pred = jnp.asarray(y_pred, dtype=jnp.float32)
    if y_true.ndim == 1:
        y_true = y_true[:, None]
    if y_pred.ndim == 1:
        y_pred = y_pred[:, None]
    return y_true, y_pred


def explained_variance_score(y_true, y_pred, sample_weight=None) -> jnp.ndarray:
    """Variance-weighted explained variance (sklearn semantics,
    ``multioutput='uniform_average'``)."""
    y_true, y_pred = _flatten_targets(y_true, y_pred)
    diff = y_true - y_pred
    num = jnp.var(diff - jnp.mean(diff, axis=0), axis=0)
    den = jnp.var(y_true - jnp.mean(y_true, axis=0), axis=0)
    per_output = 1.0 - num / jnp.maximum(den, _EPS)
    return jnp.mean(per_output)


def r2_score(y_true, y_pred) -> jnp.ndarray:
    y_true, y_pred = _flatten_targets(y_true, y_pred)
    ss_res = jnp.sum((y_true - y_pred) ** 2, axis=0)
    ss_tot = jnp.sum((y_true - jnp.mean(y_true, axis=0)) ** 2, axis=0)
    return jnp.mean(1.0 - ss_res / jnp.maximum(ss_tot, _EPS))


def mean_squared_error(y_true, y_pred) -> jnp.ndarray:
    y_true, y_pred = _flatten_targets(y_true, y_pred)
    return jnp.mean((y_true - y_pred) ** 2)


def mean_absolute_error(y_true, y_pred) -> jnp.ndarray:
    y_true, y_pred = _flatten_targets(y_true, y_pred)
    return jnp.mean(jnp.abs(y_true - y_pred))


METRICS = {
    "explained_variance_score": explained_variance_score,
    "r2_score": r2_score,
    "mean_squared_error": mean_squared_error,
    "mean_absolute_error": mean_absolute_error,
}


# -- masked variants ---------------------------------------------------------
# Row-mask forms of the same metrics, for device-side CV where a fold's test
# rows are selected by a static-shape boolean mask instead of fancy indexing
# (fleet engine: folds and models are vmap axes, shapes must stay static).

def _masked_moments(y, mask_col):
    n = jnp.maximum(jnp.sum(mask_col), 1.0)
    mean = jnp.sum(y * mask_col, axis=0) / n
    var = jnp.sum(((y - mean) ** 2) * mask_col, axis=0) / n
    return n, mean, var


def masked_explained_variance(y_true, y_pred, mask) -> jnp.ndarray:
    m = mask[:, None].astype(jnp.float32)
    diff = (y_true - y_pred)
    _, _, num = _masked_moments(diff, m)
    _, _, den = _masked_moments(y_true, m)
    return jnp.mean(1.0 - num / jnp.maximum(den, _EPS))


def masked_r2(y_true, y_pred, mask) -> jnp.ndarray:
    m = mask[:, None].astype(jnp.float32)
    ss_res = jnp.sum(((y_true - y_pred) ** 2) * m, axis=0)
    _, mean, _ = _masked_moments(y_true, m)
    ss_tot = jnp.sum(((y_true - mean) ** 2) * m, axis=0)
    return jnp.mean(1.0 - ss_res / jnp.maximum(ss_tot, _EPS))


def masked_mse(y_true, y_pred, mask) -> jnp.ndarray:
    m = mask[:, None].astype(jnp.float32)
    n = jnp.maximum(jnp.sum(m), 1.0)
    return jnp.sum(jnp.mean((y_true - y_pred) ** 2, axis=1, keepdims=True) * m) / n


def masked_mae(y_true, y_pred, mask) -> jnp.ndarray:
    m = mask[:, None].astype(jnp.float32)
    n = jnp.maximum(jnp.sum(m), 1.0)
    return jnp.sum(jnp.mean(jnp.abs(y_true - y_pred), axis=1, keepdims=True) * m) / n


MASKED_METRICS = {
    "explained_variance_score": masked_explained_variance,
    "r2_score": masked_r2,
    "mean_squared_error": masked_mse,
    "mean_absolute_error": masked_mae,
}
