"""Tiny callables usable as ``FunctionTransformer`` funcs in YAML definitions.

Reference equivalent: ``gordo_components/model/transformer_funcs/general.py``.
"""

from __future__ import annotations

import jax.numpy as jnp


def multiplier(X, factor: float = 1.0):
    """Multiply all values by ``factor`` (reference: ``general.multiplier``)."""
    return jnp.asarray(X) * factor


def adder(X, addend: float = 0.0):
    return jnp.asarray(X) + addend


def log1p(X):
    return jnp.log1p(jnp.asarray(X))


def expm1(X):
    return jnp.expm1(jnp.asarray(X))
