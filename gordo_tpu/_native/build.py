"""Compile-on-first-use loader for the C pieces (no pybind11 in-image;
ctypes over a plain shared object keeps the toolchain requirement to
``cc`` alone)."""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
import tempfile
from typing import Optional

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_fastjson: Optional[ctypes.CDLL] = None
_fastjson_failed = False


def _shared_object_path(source: str, tag: str) -> str:
    """Cache path keyed by source hash — editing the .c file rebuilds."""
    digest = hashlib.sha256(source.encode()).hexdigest()[:16]
    return os.path.join(_HERE, f"{tag}-{digest}.so")


def _compile(src_path: str, out_path: str) -> None:
    """cc -O2 -shared -fPIC, atomically installed (parallel importers race)."""
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
    os.close(fd)
    try:
        subprocess.run(
            ["cc", "-O2", "-shared", "-fPIC", src_path, "-o", tmp, "-lm"],
            check=True,
            capture_output=True,
            timeout=120,
        )
        os.replace(tmp, out_path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load_fastjson() -> Optional[ctypes.CDLL]:
    """The fastjson library, or None when native is unavailable (the codec
    then falls back to stdlib json — slower, same output contract)."""
    global _fastjson, _fastjson_failed
    if _fastjson is not None or _fastjson_failed:
        return _fastjson
    src_path = os.path.join(_HERE, "fastjson.c")
    try:
        with open(src_path) as f:
            source = f.read()
        so_path = _shared_object_path(source, "fastjson")
        if not os.path.exists(so_path):
            _compile(src_path, so_path)
            for stale in os.listdir(_HERE):  # drop superseded builds
                if (
                    stale.startswith("fastjson-")
                    and stale.endswith(".so")
                    and os.path.join(_HERE, stale) != so_path
                ):
                    try:
                        os.unlink(os.path.join(_HERE, stale))
                    except OSError:
                        pass
        lib = ctypes.CDLL(so_path)
        for name, arg0 in (
            ("fj_encode_f32", ctypes.POINTER(ctypes.c_float)),
            ("fj_encode_f64", ctypes.POINTER(ctypes.c_double)),
        ):
            fn = getattr(lib, name)
            fn.restype = ctypes.c_long
            fn.argtypes = [arg0, ctypes.c_long, ctypes.c_long, ctypes.c_char_p]
        _fastjson = lib
    except Exception:
        logger.exception("fastjson native build failed; stdlib json fallback")
        _fastjson_failed = True
    return _fastjson
