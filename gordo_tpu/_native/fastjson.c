/* fastjson: JSON encoding of float arrays, the serving hot path.
 *
 * Reference equivalent: none — the reference (pure Python, SURVEY.md §3
 * "Native-code inventory: EMPTY") serialized responses via
 * ``ndarray.tolist()`` + Flask ``jsonify``, which tops out around 1.6M
 * floats/s.  At TPU serving rates the JSON codec, not the model, bounds
 * HTTP throughput (measured r4: a 64-machine bulk response cost ~2.3s of
 * stdlib JSON vs ~0.4s of device compute), so the codec moves to C.
 *
 * Formatting contract:
 * - float32 arrays print with %.9g  (9 significant digits round-trips any
 *   binary32 value through a correctly-rounding parser)
 * - float64 arrays print with %.17g (same property for binary64)
 * - NaN/±Infinity print as NaN/Infinity/-Infinity, matching the stdlib
 *   ``json.dumps`` behavior the previous implementation had.
 *
 * Build: cc -O2 -shared -fPIC fastjson.c -o fastjson.so (see build.py;
 * loaded via ctypes — no pybind11 in this image).
 */

#include <math.h>
#include <stdint.h>
#include <stdio.h>
#include <string.h>

static long emit_double(double v, int prec, char *out) {
    if (isnan(v)) {
        memcpy(out, "NaN", 3);
        return 3;
    }
    if (isinf(v)) {
        if (v > 0) {
            memcpy(out, "Infinity", 8);
            return 8;
        }
        memcpy(out, "-Infinity", 9);
        return 9;
    }
    return (long)snprintf(out, 32, "%.*g", prec, v);
}

/* Encode a contiguous array as a JSON array (cols == 0: 1-D of `rows`
 * values) or array-of-arrays (2-D rows x cols).  `out` must hold at least
 * rows*max(cols,1)*26 + rows*2 + 16 bytes.  Returns bytes written. */
static long encode_f64_prec(const double *a, long rows, long cols, int prec,
                            char *out) {
    char *p = out;
    if (cols == 0) {
        *p++ = '[';
        for (long i = 0; i < rows; ++i) {
            if (i) *p++ = ',';
            p += emit_double(a[i], prec, p);
        }
        *p++ = ']';
        return p - out;
    }
    *p++ = '[';
    for (long r = 0; r < rows; ++r) {
        if (r) *p++ = ',';
        *p++ = '[';
        const double *row = a + r * cols;
        for (long c = 0; c < cols; ++c) {
            if (c) *p++ = ',';
            p += emit_double(row[c], prec, p);
        }
        *p++ = ']';
    }
    *p++ = ']';
    return p - out;
}

long fj_encode_f64(const double *a, long rows, long cols, char *out) {
    return encode_f64_prec(a, rows, cols, 17, out);
}

/* --- fast float32 formatter ---------------------------------------------
 *
 * Shortest-practical round-trip text for binary32 without snprintf
 * (measured ~4M floats/s with %.9g vs ~40M with this): scale |v| into
 * [1e8, 1e9) with a double power-of-ten multiply, round to a 9-digit
 * integer, trim trailing zeros, and lay out %g-style fixed/exponential
 * notation.  Why this is exact for float32: the 9-digit integer fits a
 * double exactly (< 2^53), the table powers err by <= 1 double-ulp
 * (~1e-16 relative), and half-ulp-of-9th-digit is ~5e-10 relative — three
 * million times coarser — so the rounded 9 significant digits are the
 * correctly-rounded decimal, and 9 correct significant digits round-trip
 * any binary32.  (NOT valid for float64, which keeps %.17g above.)
 */

static const double POW10[] = {
    1e-30, 1e-29, 1e-28, 1e-27, 1e-26, 1e-25, 1e-24, 1e-23, 1e-22, 1e-21,
    1e-20, 1e-19, 1e-18, 1e-17, 1e-16, 1e-15, 1e-14, 1e-13, 1e-12, 1e-11,
    1e-10, 1e-9,  1e-8,  1e-7,  1e-6,  1e-5,  1e-4,  1e-3,  1e-2,  1e-1,
    1e0,   1e1,   1e2,   1e3,   1e4,   1e5,   1e6,   1e7,   1e8,   1e9,
    1e10,  1e11,  1e12,  1e13,  1e14,  1e15,  1e16,  1e17,  1e18,  1e19,
    1e20,  1e21,  1e22,  1e23,  1e24,  1e25,  1e26,  1e27,  1e28,  1e29,
    1e30,  1e31,  1e32,  1e33,  1e34,  1e35,  1e36,  1e37,  1e38,  1e39,
    1e40,  1e41,  1e42,  1e43,  1e44,  1e45,  1e46,  1e47,  1e48,  1e49,
    1e50,  1e51,  1e52,  1e53,
};
#define POW10_BIAS 30 /* POW10[POW10_BIAS + k] == 10^k, k in [-30, 53] */

static long fmt_f32(float f, char *out) {
    char *p = out;
    if (isnan(f)) {
        memcpy(p, "NaN", 3);
        return 3;
    }
    if (signbit(f)) { /* not f < 0: -0.0 must keep its sign like repr() */
        *p++ = '-';
        f = -f;
    }
    if (isinf(f)) {
        memcpy(p, "Infinity", 8);
        return (p - out) + 8;
    }
    if (f == 0.0f) {
        memcpy(p, "0.0", 3);
        return (p - out) + 3;
    }
    double v = (double)f;
    int e10 = (int)floor(log10(v));
    /* scale to [1e8, 1e9): 9 significant digits */
    uint64_t d = (uint64_t)(v * POW10[POW10_BIAS + 8 - e10] + 0.5);
    if (d >= 1000000000ULL) { /* log10 underestimated (e.g. exactly 1eN) */
        e10 += 1;
        d = (uint64_t)(v * POW10[POW10_BIAS + 8 - e10] + 0.5);
    } else if (d < 100000000ULL) { /* log10 overestimated */
        e10 -= 1;
        d = (uint64_t)(v * POW10[POW10_BIAS + 8 - e10] + 0.5);
        if (d >= 1000000000ULL) { /* rounding pushed it back up */
            e10 += 1;
            d = (uint64_t)(v * POW10[POW10_BIAS + 8 - e10] + 0.5);
        }
    }
    char digits[9];
    for (int i = 8; i >= 0; --i) {
        digits[i] = (char)('0' + (d % 10));
        d /= 10;
    }
    int ndig = 9;
    while (ndig > 1 && digits[ndig - 1] == '0')
        --ndig;
    /* %g-style layout: fixed for -5 < e10 < 9, exponential otherwise
     * (always with a '.' or an 'e' so the token parses as a JSON float) */
    if (e10 >= ndig - 1 && e10 < 9) { /* integer-valued layout: 123.0 */
        for (int i = 0; i < ndig; ++i)
            *p++ = digits[i];
        for (int i = ndig; i <= e10; ++i)
            *p++ = '0';
        *p++ = '.';
        *p++ = '0';
    } else if (e10 >= 0 && e10 < 9) { /* 12.345 */
        for (int i = 0; i <= e10; ++i)
            *p++ = digits[i];
        *p++ = '.';
        for (int i = e10 + 1; i < ndig; ++i)
            *p++ = digits[i];
    } else if (e10 < 0 && e10 > -5) { /* 0.0012345 */
        *p++ = '0';
        *p++ = '.';
        for (int i = -1; i > e10; --i)
            *p++ = '0';
        for (int i = 0; i < ndig; ++i)
            *p++ = digits[i];
    } else { /* 1.2345e-07 */
        *p++ = digits[0];
        *p++ = '.';
        if (ndig == 1) {
            *p++ = '0';
        } else {
            for (int i = 1; i < ndig; ++i)
                *p++ = digits[i];
        }
        *p++ = 'e';
        int e = e10;
        if (e < 0) {
            *p++ = '-';
            e = -e;
        } else {
            *p++ = '+';
        }
        if (e >= 10) {
            *p++ = (char)('0' + e / 10);
            *p++ = (char)('0' + e % 10);
        } else {
            *p++ = '0';
            *p++ = (char)('0' + e);
        }
    }
    return p - out;
}

long fj_encode_f32(const float *a, long rows, long cols, char *out) {
    char *p = out;
    if (cols == 0) {
        *p++ = '[';
        for (long i = 0; i < rows; ++i) {
            if (i) *p++ = ',';
            p += fmt_f32(a[i], p);
        }
        *p++ = ']';
        return p - out;
    }
    *p++ = '[';
    for (long r = 0; r < rows; ++r) {
        if (r) *p++ = ',';
        *p++ = '[';
        const float *row = a + r * cols;
        for (long c = 0; c < cols; ++c) {
            if (c) *p++ = ',';
            p += fmt_f32(row[c], p);
        }
        *p++ = ']';
    }
    *p++ = ']';
    return p - out;
}
