"""Native (C) runtime pieces, loaded via ctypes.

The reference is pure Python (SURVEY.md §3: "Native-code inventory:
EMPTY"); this package exists because at TPU serving rates the HTTP JSON
codec — not the model — bounds throughput.  Components compile on first
use with the in-image ``cc`` and cache next to the source; every consumer
has a pure-Python fallback, so a missing/broken toolchain degrades to the
stdlib path instead of failing.
"""

from gordo_tpu._native.build import load_fastjson  # noqa: F401
